"""Core runtime microbenchmarks, named after the reference's harness.

Reference: ``python/ray/_private/ray_perf.py:93-315`` — the nightly
microbenchmark suite whose metric names (single-client tasks sync/async,
1:1 / 1:n actor calls, put/get throughput, ``ray.wait``) BASELINE.md asks
this build to reproduce. Prints one JSON line per metric plus a combined
line; ``python bench_core.py`` runs everything on a local cluster.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def bench_environment() -> dict:
    """Record the conditions the benchmark ran under.

    Round 4's core numbers collapsed ~5x purely from VM contention and
    nothing in the output could tell that apart from a regression
    (VERDICT r4 weakness #2).  Three signals fix that:

    - ``cpu_count``: 1-core boxes serialize the head/worker/driver trio.
    - ``loadavg``: load already on the box when we started.
    - ``spin_canary_mops``: a fixed pure-Python spin loop measured twice
      (before/after could also drift); on an uncontended box this is a
      property of the interpreter + CPU only, so a low value directly
      measures how much CPU the bench process actually received.
    """
    def spin_mops() -> float:
        n = 2_000_000
        t0 = time.perf_counter()
        x = 0
        for i in range(n):
            x += i
        dt = time.perf_counter() - t0
        return round(n / dt / 1e6, 2)

    try:
        load = tuple(round(v, 2) for v in os.getloadavg())
    except OSError:  # pragma: no cover - non-unix
        load = None
    return {
        "cpu_count": os.cpu_count(),
        "loadavg_1_5_15": load,
        "spin_canary_mops": spin_mops(),
    }


def timeit(name: str, fn, unit: str = "per_s", warmup=True, windows: int = 3,
           extra: dict = None) -> dict:
    """Median of three measurement windows (like bench.py's TPU metric):
    single short windows on a shared VM swing ±40% with scheduler noise,
    which round 3 initially misread as regressions.  ``extra`` merges
    qualifier tags into the printed record (e.g. ``loopback: true``)."""
    if warmup:
        fn()
    rates = []
    for _ in range(max(windows, 1)):
        t0 = time.perf_counter()
        n = fn()
        rates.append(n / (time.perf_counter() - t0))
    rec = {"metric": name, "value": round(sorted(rates)[len(rates) // 2], 2), "unit": unit}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)
    return rec


def main() -> list[dict]:
    import ray_tpu

    env = bench_environment()
    print(json.dumps({"metric": "bench_environment", **env}), flush=True)

    ray_tpu.init(num_cpus=8)
    results = []

    # -- tasks (ray_perf: "single client tasks sync/async") ----------------
    @ray_tpu.remote
    def noop():
        return None

    def tasks_sync(n=600):
        for _ in range(n):
            ray_tpu.get(noop.remote())
        return n

    def tasks_async(n=3000):
        ray_tpu.get([noop.remote() for _ in range(n)])
        return n

    results.append(timeit("single_client_tasks_sync", tasks_sync))
    results.append(timeit("single_client_tasks_async", tasks_async))

    # -- actor calls (ray_perf: "1:1 actor calls sync/async", "1:n") -------
    @ray_tpu.remote
    class A:
        def noop(self):
            return None

    a = A.remote()
    ray_tpu.get(a.noop.remote())

    def actor_sync(n=600):
        for _ in range(n):
            ray_tpu.get(a.noop.remote())
        return n

    def actor_async(n=3000):
        ray_tpu.get([a.noop.remote() for _ in range(n)])
        return n

    results.append(timeit("single_client_actor_calls_sync", actor_sync))
    results.append(timeit("single_client_actor_calls_async", actor_async))

    actors = [A.remote() for _ in range(4)]
    ray_tpu.get([x.noop.remote() for x in actors])

    def actor_one_to_n(n=250):
        ray_tpu.get([x.noop.remote() for x in actors for _ in range(n)])
        return n * len(actors)

    results.append(timeit("client_1_to_4_actor_calls_async", actor_one_to_n))

    # -- object plane (ray_perf: put/get GB/s) -----------------------------
    small = np.zeros(1024, np.uint8)

    def put_small(n=500):
        for _ in range(n):
            ray_tpu.put(small)
        return n

    results.append(timeit("single_client_put_calls_1kb", put_small))

    big = np.zeros(10 * 1024 * 1024, np.uint8)  # 10 MB

    def put_gigabytes(n=20):
        refs = [ray_tpu.put(big) for _ in range(n)]
        ray_tpu.get(refs[-1])
        return n * big.nbytes / 1e9

    results.append(timeit("single_client_put_gigabytes", put_gigabytes, unit="GB_per_s"))

    refs_big = [ray_tpu.put(big) for _ in range(8)]

    def get_gigabytes(n=40):
        total = 0
        for i in range(n):
            out = ray_tpu.get(refs_big[i % len(refs_big)])
            total += int(out[::65536].sum())  # touch pages: measure real reads
        return n * big.nbytes / 1e9

    results.append(timeit("single_client_get_gigabytes", get_gigabytes, unit="GB_per_s"))

    # -- wait (ray_perf: "1:1 ray.wait on 1k refs") ------------------------
    refs_1k = [noop.remote() for _ in range(1000)]
    ray_tpu.get(refs_1k)

    def wait_1k(n=100):
        for _ in range(n):
            ray_tpu.wait(refs_1k, num_returns=1000, timeout=10)
        return n

    results.append(timeit("single_client_wait_1k_refs", wait_1k))

    # -- scalability envelope (reference release/benchmarks/README.md:
    # queued tasks, actor fan-out, large-object broadcast) ------------------
    def queued_100k(n=100_000):
        ray_tpu.get([noop.remote() for _ in range(n)], timeout=600)
        return n

    results.append(timeit("envelope_queued_tasks_100k", queued_100k,
                          warmup=False, windows=1))

    @ray_tpu.remote(num_cpus=0)
    class E:
        def ping(self):
            return 1

    def actor_wave(n=200):
        wave = [E.remote() for _ in range(n)]
        assert ray_tpu.get([x.ping.remote() for x in wave], timeout=600) == [1] * n
        for x in wave:
            ray_tpu.kill(x)
        return n

    results.append(timeit("envelope_actors_spawned", actor_wave,
                          warmup=False, windows=1))

    def broadcast_256mb(n=8):
        blob_ref = ray_tpu.put(np.ones((256 << 20) // 8, np.float64))

        @ray_tpu.remote
        def read(b):
            return b.nbytes

        sizes = ray_tpu.get([read.remote(blob_ref) for _ in range(n)], timeout=300)
        return sum(sizes) / 1e9  # logical GB fanned out

    results.append(timeit("envelope_broadcast_256mb_x8", broadcast_256mb,
                          unit="GB_per_s", warmup=False, windows=1))

    ray_tpu.shutdown()
    env["spin_canary_mops_after"] = bench_environment()["spin_canary_mops"]
    print(
        json.dumps(
            {
                "metric": "core_microbench",
                "value": len(results),
                "unit": "metrics",
                "env": env,
                "detail": {r["metric"]: [r["value"], r["unit"]] for r in results},
            }
        ),
        flush=True,
    )
    return results


def obs_ab_main() -> dict:
    """Core-plane observability A/B probe (``--obs-ab``): the
    task-submission + object-plane microbenchmarks most implicated in the
    BENCH_r04 4-8x core collapse, run ONCE under whatever
    ``RAY_TPU_EVENTS`` / ``RAY_TPU_METRICS_SERIES`` the caller exported.
    ``bench.py`` invokes this twice — obs ON and obs OFF — in separate
    subprocesses (both knobs are read at import) and emits both numbers
    in the round JSON, so the recorder/series share of any core
    regression is attributable from the bench record alone, before the
    dedicated perf PR profiles the hot path."""
    import ray_tpu
    from ray_tpu._private import events as _events

    env = bench_environment()

    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def noop():
        return None

    def tasks_sync(n=600):
        for _ in range(n):
            ray_tpu.get(noop.remote())
        return n

    def tasks_async(n=3000):
        ray_tpu.get([noop.remote() for _ in range(n)])
        return n

    small = np.zeros(1024, np.uint8)

    def put_small(n=500):
        for _ in range(n):
            ray_tpu.put(small)
        return n

    results = [
        timeit("obs_ab_tasks_sync", tasks_sync),
        timeit("obs_ab_tasks_async", tasks_async),
        timeit("obs_ab_put_calls_1kb", put_small),
    ]
    ray_tpu.shutdown()
    rec = {
        "metric": "core_obs_ab",
        "events_enabled": _events.enabled(),
        "series_enabled": os.environ.get("RAY_TPU_METRICS_SERIES", "1")
        not in ("0", "false", "off"),
        "trace_sample": os.environ.get("RAY_TPU_TRACE_SAMPLE", "1"),
        "env": env,
        "detail": {r["metric"]: r["value"] for r in results},
    }
    print(json.dumps(rec), flush=True)
    return rec


def batched_main() -> dict:
    """Batched-path probe (``--batched``): tasks/s on the pipelined
    submit/reply plane (ISSUE 14) plus the achieved batch sizes and
    per-hop waterfall percentiles, in ONE JSON record (the last stdout
    line). The CI waterfall-probe job uploads it next to the
    core-obs-ab artifact so the IPC trajectory — hop microseconds AND
    how much batching the plane actually achieves — is recorded per PR.
    ``batched_tasks_nested_async`` fans out from a WORKER, which is the
    path that rides submit_batch windows; driver-side async bursts ride
    coalesced dispatch + reply batches."""
    import ray_tpu
    from ray_tpu.util import metrics as um

    env = bench_environment()

    ray_tpu.init(num_cpus=8)

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    def fan(n):
        ray_tpu.get([noop.remote() for _ in range(n)])
        return n

    def tasks_sync(n=600):
        for _ in range(n):
            ray_tpu.get(noop.remote())
        return n

    def tasks_async(n=3000):
        ray_tpu.get([noop.remote() for _ in range(n)])
        return n

    def tasks_nested_async(n=2000):
        return ray_tpu.get(fan.remote(n))

    results = [
        timeit("batched_tasks_sync", tasks_sync),
        timeit("batched_tasks_async", tasks_async),
        timeit("batched_tasks_nested_async", tasks_nested_async),
    ]

    def hist(name: str):
        for v in um.histogram_percentiles(name).get(name, {}).values():
            return {"p50": v.get("p50"), "p99": v.get("p99"), "count": v.get("count")}
        return None

    # per-hop legs from a small TRACED burst, computed from the recent-
    # record ring so ONLY the probe's records count (the head's leg
    # histograms are process-lifetime and the nested arm's worker-side
    # roots sample; the throughput arms themselves stay rootless so
    # stamps never perturb the tasks/s numbers)
    from ray_tpu._private.runtime import get_ctx
    from ray_tpu.util import tracing

    with tracing.trace_context():
        for _ in range(250):
            ray_tpu.get(noop.remote())
    recent = get_ctx().call("waterfall", recent=250).get("recent", [])[-250:]

    def leg_pcts(recs: list) -> dict:
        out = {}
        legs = {k for r in recs for k in r.get("legs", {})}
        for leg in sorted(legs):
            vals = sorted(r["legs"][leg] for r in recs if leg in r.get("legs", {}))
            if vals:
                out[leg] = {
                    "p50": vals[len(vals) // 2],
                    "p99": vals[min(len(vals) - 1, int(len(vals) * 0.99))],
                    "count": len(vals),
                }
        return out

    # read metrics BEFORE shutdown: the registry dies with the cluster
    batch_hists = {
        "core_submit_batch_size": hist("core_submit_batch_size"),
        "core_reply_batch_size": hist("core_reply_batch_size"),
    }
    ray_tpu.shutdown()
    env["spin_canary_mops_after"] = bench_environment()["spin_canary_mops"]
    rec = {
        "metric": "core_batched_path",
        "env": env,
        "detail": {r["metric"]: r["value"] for r in results},
        "batch_hists": batch_hists,
        "waterfall_legs": leg_pcts(recent),
    }
    print(json.dumps(rec), flush=True)
    return rec


def data_plane_main() -> dict:
    """Data-plane probe (``--data-plane``): put/get MB/s at 1KB/64KB/1MB
    across a LOCAL arm (driver + same-machine workers: the ISSUE 18
    shm-locator path) and a REMOTE arm (loopback NodeAgents with
    RAY_TPU_FORCE_DATA_PLANE=1: the peer-to-peer TCP fetch path), plus
    the locality-scheduler placement fraction and a tasks_async canary.
    One JSON record as the last stdout line (the data-plane.json CI
    artifact). ``local_worker_put_*`` is the arm ISSUE 18 targets: puts
    originate in a WORKER process, so before the shm plane every value
    in the (8KB, 100KB] band rode the control socket inline — twice.
    Set RAY_TPU_CORE_SHM_INLINE_THRESHOLD=102400 and
    RAY_TPU_CORE_PUT_PIPELINE=0 to restore that path on the same box
    (the BENCH_r09 paired "before" arm)."""
    import tempfile

    import ray_tpu

    env = bench_environment()
    env["core_shm_inline_threshold"] = int(
        os.environ.get("RAY_TPU_CORE_SHM_INLINE_THRESHOLD", 8 * 1024)
    )
    env["core_put_pipeline"] = os.environ.get(
        "RAY_TPU_CORE_PUT_PIPELINE", "1"
    ).lower() not in ("0", "false", "no")
    sizes = {"1kb": 1024, "64kb": 64 * 1024, "1mb": 1024 * 1024}
    results = []

    # ---- local arm: single-machine cluster, same-node shm path -----------
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    def wput(n, nb):
        b = np.ones(nb, np.uint8)
        for _ in range(n):
            ray_tpu.put(b)
        return n

    @ray_tpu.remote
    def noop():
        return None

    for name, nb in sizes.items():
        blob = np.ones(nb, np.uint8)
        # 32MB per window: sub-20ms windows on a contended 1-core box swing
        # 2x with scheduler noise and drown the arm-vs-arm signal
        reps = max(8, min(512, (32 << 20) // nb))

        def put_burst(n=reps, b=blob):
            for _ in range(n):
                ray_tpu.put(b)
            return n * b.nbytes / 1e6

        results.append(timeit(f"local_driver_put_{name}", put_burst, unit="MB_per_s"))

        pool = [ray_tpu.put(blob) for _ in range(8)]

        def get_burst(n=reps, pool=pool, nb=nb):
            t = 0
            for i in range(n):
                t += int(ray_tpu.get(pool[i % len(pool)])[::4096].sum())
            assert t
            return n * nb / 1e6

        results.append(timeit(f"local_driver_get_{name}", get_burst, unit="MB_per_s"))

        def worker_put(n=reps, nb=nb):
            ray_tpu.get(wput.remote(n, nb), timeout=120)
            return n * nb / 1e6

        results.append(timeit(f"local_worker_put_{name}", worker_put, unit="MB_per_s"))

    # regression canary: the locality pass must not tax argless dispatch
    def tasks_async(n=2000):
        ray_tpu.get([noop.remote() for _ in range(n)])
        return n

    results.append(timeit("tasks_async_canary", tasks_async))
    ray_tpu.shutdown()

    # ---- remote arm: loopback agents, forced peer-to-peer TCP fetch ------
    from ray_tpu._private.config import resolve_authkey
    from ray_tpu._private.head import Head
    from ray_tpu._private.node_agent import NodeAgent

    prev_force = os.environ.get("RAY_TPU_FORCE_DATA_PLANE")
    os.environ["RAY_TPU_FORCE_DATA_PLANE"] = "1"
    authkey = resolve_authkey()
    session = tempfile.mkdtemp(prefix="ray_tpu_bench_dp_")
    head = Head(os.path.join(session, "head.sock"), authkey=authkey)
    head.start()
    host, port = head.listen_tcp("127.0.0.1", 0)
    head.add_node({"CPU": 0.0})
    addr = f"{host}:{port}"
    a = NodeAgent(addr, authkey, resources={"CPU": 2.0, "nodeA": 10.0}).start()
    b = NodeAgent(addr, authkey, resources={"CPU": 2.0, "nodeB": 10.0}).start()
    locality = None
    loc_hits = loc_total = 0
    try:
        ray_tpu.init(address=addr)

        @ray_tpu.remote(resources={"nodeA": 0.01})
        def produce(nb):
            return np.ones(nb, np.uint8)

        @ray_tpu.remote(num_cpus=1)
        def where(x):
            return ray_tpu.get_runtime_context().get_node_id()

        for name, nb in sizes.items():
            reps = max(8, min(64, (16 << 20) // nb))
            pool = [produce.remote(nb) for _ in range(4)]
            ray_tpu.wait(pool, num_returns=len(pool), timeout=60)

            # forced-dp fetches are NOT reader-cached: every get below is a
            # full TCP fetch from nodeA's data server, so pool reuse is fair
            def remote_get(n=reps, pool=pool, nb=nb):
                t = 0
                for i in range(n):
                    t += int(ray_tpu.get(pool[i % len(pool)], timeout=60)[::4096].sum())
                assert t
                return n * nb / 1e6

            # loopback, not a network benchmark: both "remote" agents live
            # on this host, so remote_get MB/s measures the TCP data-plane
            # software path (chunking, recv_bytes_into, dispatch) with no
            # NIC in the loop — compare arms against each other, never
            # against real cross-host bandwidth
            results.append(timeit(
                f"remote_get_{name}", remote_get, unit="MB_per_s",
                extra={
                    "loopback": True,
                    "note": "agents share the bench host; software-path "
                            "MB/s, not network bandwidth",
                },
            ))

        # locality fraction: unconstrained single-arg consumers should land
        # on the node already holding the bytes (acceptance bar: >= 0.9)
        data = produce.remote(64 * 1024)
        ray_tpu.wait([data], timeout=60)
        placed = [ray_tpu.get(where.remote(data), timeout=60) for _ in range(20)]
        locality = placed.count(a.node_id_bin.hex()) / len(placed)
        with head.lock:
            loc_hits, loc_total = head._loc_hits, head._loc_total
    finally:
        if prev_force is None:
            os.environ.pop("RAY_TPU_FORCE_DATA_PLANE", None)
        else:
            os.environ["RAY_TPU_FORCE_DATA_PLANE"] = prev_force
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        a.shutdown()
        b.shutdown()
        head.shutdown()

    env["spin_canary_mops_after"] = bench_environment()["spin_canary_mops"]
    rec = {
        "metric": "core_data_plane",
        "value": len(results),
        "unit": "metrics",
        "env": env,
        "detail": {r["metric"]: r["value"] for r in results},
        "locality_fraction": locality,
        "locality_sched": {"hits": loc_hits, "total": loc_total},
    }
    print(json.dumps(rec), flush=True)
    return rec


if __name__ == "__main__":
    import sys

    if "--obs-ab" in sys.argv:
        obs_ab_main()
    elif "--batched" in sys.argv:
        batched_main()
    elif "--data-plane" in sys.argv:
        data_plane_main()
    else:
        main()
