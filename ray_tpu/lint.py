"""Public entry point: ``python -m ray_tpu.lint [paths]``.

Thin shim over :mod:`ray_tpu._lint` so the implementation stays private
(mirrors the ``_private``/public split used across the package). See
LINTING.md for the rule catalog, suppression syntax and baseline workflow.
"""

from ray_tpu._lint.cli import main  # noqa: F401

if __name__ == "__main__":
    raise SystemExit(main())
