"""Staging buffer between the rollout plane and the async learner.

Not a classic replay buffer: trajectories are consumed (at most) once,
in arrival order, and the learner BLOCKS on ``take`` until a full batch
is staged — the asynchrony lives in the fact that rollout actors keep
generating (and the poller thread keeps staging) while the learner is
inside its update step. Bounded: when generation outruns learning the
OLDEST trajectories drop first (they would be the stalest — dropping
them is the cheap half of staleness control; the version gate in
``rlhf.algorithm`` handles what the cap lets through).

Thread-safe; owns no thread of its own.
"""

from __future__ import annotations

import threading
from typing import Optional


class TrajectoryBuffer:
    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._items: list[dict] = []
        self._cv = threading.Condition()
        self._dropped = 0
        self._added = 0

    def add(self, trajs: list[dict]) -> None:
        with self._cv:
            self._items.extend(trajs)
            self._added += len(trajs)
            if len(self._items) > self.capacity:
                overflow = len(self._items) - self.capacity
                del self._items[:overflow]  # oldest = stalest
                self._dropped += overflow
            self._cv.notify_all()

    def take(self, n: int, timeout: Optional[float] = None) -> list[dict]:
        """Block until ``n`` trajectories are staged (or ``timeout``
        elapses — then returns whatever is there, possibly [])."""
        deadline = None if timeout is None else (
            threading.TIMEOUT_MAX if timeout < 0 else timeout
        )
        with self._cv:
            if deadline is not None:
                self._cv.wait_for(lambda: len(self._items) >= n, timeout=deadline)
            else:
                self._cv.wait_for(lambda: len(self._items) >= n)
            got = self._items[:n]
            del self._items[:n]
            return got

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def stats(self) -> dict:
        with self._cv:
            return {
                "staged": len(self._items),
                "added": self._added,
                "dropped_overflow": self._dropped,
            }
