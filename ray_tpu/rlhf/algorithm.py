"""Async disaggregated RLHF: the driver that wires rollouts, sync, and
learning together, plus the pure staleness/importance math.

The loop (LlamaRL / MindSpeed RL shape, on this repo's substrate):

* N ``RolloutWorker`` actors generate CONTINUOUSLY — a driver-side
  poller thread harvests finished trajectories, scores them with the
  user's ``reward_fn``, stages them in a bounded ``TrajectoryBuffer``,
  and refills each worker back to its in-flight target;
* the learner (``rlhf.learner`` in the shared ``rl.learner`` machinery)
  consumes batches from the buffer: staleness admission gate →
  group-relative (GRPO) advantages → clipped-surrogate update with
  importance correction from the captured behavior logprobs;
* after every update the new weights PUBLISH through the object plane
  (``rlhf.sync.publish_weights``) and fan out to the workers
  asynchronously — generation never drains, trajectories submitted
  before the swap complete under mixed weights with exact per-token
  behavior logprobs, and their version stamps let the gate decide.

Off-policy correction is layered: the importance ratio corrects WITHIN
the trust region (clipped), the staleness gate bounds how far outside it
a trajectory may originate — ``drop`` discards anything more than
``max_staleness`` versions old, ``downweight`` decays its sample weight
instead (both unit-pinned in tests/test_rlhf.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

import numpy as np

from ray_tpu._private import events as _events
from ray_tpu._private.log_util import warn_throttled
from ray_tpu.rl.sample_batch import SampleBatch
from ray_tpu.rlhf.buffer import TrajectoryBuffer
from ray_tpu.rlhf.learner import make_learner_group
from ray_tpu.rlhf.metrics import rlhf_metrics
from ray_tpu.rlhf.rollout import RolloutGroup
from ray_tpu.rlhf.sync import publish_weights


# ---------------------------------------------------------------------------
# pure math (golden-testable without a cluster)
# ---------------------------------------------------------------------------


def staleness_weights(
    ages,
    max_staleness: int,
    mode: str = "drop",
    halflife: float = 1.0,
) -> np.ndarray:
    """Per-trajectory sample weight from version age (learner version
    minus the trajectory's ``weights_version`` stamp).

    * ``drop`` — weight 1 while ``age <= max_staleness``, else 0.
    * ``downweight`` — weight 1 while ``age <= max_staleness``, then
      ``0.5 ** ((age - max_staleness) / halflife)``: every ``halflife``
      versions past the gate halves the trajectory's influence instead
      of discarding the sample outright (the LlamaRL-style soft gate for
      scarce data).

    Negative ages (a trajectory stamped by a NEWER engine than the
    learner — possible when an apply lands before the learner's publish
    bookkeeping) count as age 0.
    """
    ages = np.maximum(np.asarray(ages, np.float64), 0.0)
    if mode == "drop":
        w = (ages <= max_staleness).astype(np.float32)
    elif mode == "downweight":
        over = np.maximum(ages - max_staleness, 0.0)
        w = np.power(0.5, over / max(halflife, 1e-9)).astype(np.float32)
    else:
        raise ValueError(f"unknown staleness mode {mode!r}")
    return w


def importance_ratios(behavior_logp, current_logp, clip: Optional[float] = None):
    """``exp(current - behavior)`` per token, optionally clipped into
    ``[1-clip, 1+clip]`` (the PPO trust region). Pure numpy — the golden
    tests pin this against hand-computed values; the jitted learner loss
    computes the same quantity on device."""
    r = np.exp(np.asarray(current_logp, np.float64) - np.asarray(behavior_logp, np.float64))
    if clip is not None:
        r = np.clip(r, 1.0 - clip, 1.0 + clip)
    return r.astype(np.float32)


def group_advantages(rewards) -> np.ndarray:
    """GRPO group-relative advantage: standardize rewards within the
    consumed batch (no value net). A zero-variance batch yields zero
    advantages — no evidence, no update."""
    r = np.asarray(rewards, np.float64)
    std = r.std()
    if std < 1e-8:
        return np.zeros(len(r), np.float32)
    return ((r - r.mean()) / std).astype(np.float32)


# ---------------------------------------------------------------------------
# config + driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RLHFConfig:
    """Knobs for one async RLHF run. ``model_cfg`` is the policy's
    ``GPTConfig`` (shared by learner and rollout engines); ``prompts``
    cycle round-robin onto workers; ``reward_fn(prompt, tokens) ->
    float`` scores a finished trajectory on the driver."""

    model_cfg: object = None
    engine_config: object = None
    prompts: list = None
    reward_fn: Callable = None
    # rollout plane
    num_rollout_workers: int = 1
    remote_rollouts: bool = True
    rollout_inflight: int = 8      # in-flight requests to hold per worker
    max_tokens: int = 8
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    num_cpus_per_worker: float = 1
    warmup: bool = True
    # learner plane
    train_batch: int = 16          # trajectories per update
    lr: float = 1e-2
    grad_clip: Optional[float] = 1.0
    clip_param: float = 0.2
    kl_coeff: float = 0.0
    remote_learner: bool = False
    # staleness policy
    max_staleness: int = 4
    staleness_mode: str = "drop"   # "drop" | "downweight"
    staleness_halflife: float = 1.0
    # plumbing
    buffer_capacity: int = 512
    chunk_bytes: int = 8 << 20
    batch_timeout_s: float = 120.0
    poll_interval_s: float = 0.005
    sync_ack_timeout_s: float = 60.0
    seed: int = 0

    def validate(self) -> "RLHFConfig":
        if self.model_cfg is None:
            raise ValueError("model_cfg is required")
        if not self.prompts:
            raise ValueError("prompts must be a non-empty list of token lists")
        if self.reward_fn is None:
            raise ValueError("reward_fn is required")
        if self.max_tokens < 1 or self.train_batch < 1:
            raise ValueError("max_tokens and train_batch must be >= 1")
        if self.staleness_mode not in ("drop", "downweight"):
            raise ValueError(f"unknown staleness mode {self.staleness_mode!r}")
        return self


class Algorithm:
    """``rlhf.Algorithm`` — build once, ``train(n)`` for n async
    iterations, ``shutdown()``. See the module doc for the loop shape."""

    def __init__(self, config: RLHFConfig):
        self.config = config.validate()
        cfg = self.config
        self._version = 0
        self._stop = threading.Event()
        self._buffer = TrajectoryBuffer(cfg.buffer_capacity)
        self._prompt_i = 0
        self._pending_acks: list = []   # (version, ack refs) awaiting harvest
        self._last_batch_versions: list[int] = []
        # fixed learner shapes: pad every batch to these so the update
        # jit traces exactly once
        self._T = max(len(p) for p in cfg.prompts) + cfg.max_tokens
        self._O = cfg.max_tokens
        if getattr(cfg.model_cfg, "seq_len", self._T) < self._T:
            raise ValueError(
                f"model seq_len {cfg.model_cfg.seq_len} < prompt+max_tokens "
                f"{self._T}"
            )

        self.learner_group = make_learner_group(
            cfg.model_cfg, lr=cfg.lr, grad_clip=cfg.grad_clip,
            clip_param=cfg.clip_param, kl_coeff=cfg.kl_coeff,
            seed=cfg.seed, remote=cfg.remote_learner,
        )
        self.rollouts = RolloutGroup(
            num_workers=cfg.num_rollout_workers,
            worker_kwargs=dict(
                model="gpt", model_cfg=cfg.model_cfg,
                engine_config=cfg.engine_config, seed=cfg.seed,
                sample_seed_base=cfg.seed, warmup=cfg.warmup,
            ),
            remote=cfg.remote_rollouts,
            num_cpus=cfg.num_cpus_per_worker,
        )
        try:
            # version 0 = the learner's init, everywhere: push synchronously
            # ONCE before any trajectory exists (startup is the one moment
            # draining is free), then never block on a push again
            update0 = publish_weights(
                self.learner_group.get_weights(), 0, chunk_bytes=cfg.chunk_bytes
            )
            self._await_acks(self.rollouts.push_weights(update0), 0)
            # prime every worker to its in-flight target, then keep it
            # there from the poller
            for i in range(self.rollouts.num_workers):
                self._refill(i, cfg.rollout_inflight)
        except BaseException:
            # a failed bring-up must not orphan N rollout actors (the
            # caller never gets a handle to shutdown())
            self.rollouts.shutdown()
            self.learner_group.shutdown()
            raise
        self._poller = threading.Thread(
            target=self._poll_loop, name="rlhf-poller", daemon=True
        )
        self._poller.start()

    # -- rollout-side plumbing (poller thread) ------------------------------

    def _next_prompts(self, n: int) -> list:
        ps = []
        for _ in range(n):
            ps.append(self.config.prompts[self._prompt_i % len(self.config.prompts)])
            self._prompt_i += 1
        return ps

    def _refill(self, worker_idx: int, missing: int) -> None:
        if missing <= 0:
            return
        cfg = self.config
        self.rollouts.submit_to(
            worker_idx, self._next_prompts(missing),
            max_tokens=cfg.max_tokens, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p,
        )

    def _harvest_acks(self) -> None:
        """Reap weight-push acks that are ALREADY done (zero timeout —
        the overlap contract means the learner NEVER blocks on a push; a
        hung worker's ack simply stays pending until the >4 backlog cap
        drops it with a warning, and the staleness gauge/SLO rule is the
        systemic alarm). Called ONLY from the train_step caller thread
        (pushes originate there too) — keeping every ``_pending_acks``
        mutation on one thread is what makes the bookkeeping race-free;
        a poller-side reap would let a wholesale reassignment here drop
        an entry train_step just appended."""
        if not self._pending_acks or not self.config.remote_rollouts:
            self._pending_acks = []
            return
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        remaining = []
        for version, refs in self._pending_acks:
            try:
                ray_tpu.get(refs, timeout=0)
            except GetTimeoutError:
                remaining.append((version, refs))  # still applying
            except Exception as e:
                # resolved WITH an error (dead worker, version mismatch):
                # surface it and retire the entry — retrying a settled
                # failure would never succeed
                warn_throttled("rlhf sync ack", e)
        self._pending_acks = remaining

    def _poll_loop(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            try:
                trajs, pending = self.rollouts.poll()
                scored = []
                for t in trajs:
                    # poll() is destructive (the worker already forgot
                    # these), so one bad trajectory must cost ONLY itself
                    # — a raising reward_fn (0-token deadline finish, a
                    # tokenizer hiccup) never discards the whole harvest
                    try:
                        t["reward"] = float(cfg.reward_fn(t["prompt"], t["tokens"]))
                        scored.append(t)
                    except Exception as e:
                        warn_throttled("rlhf reward_fn", e)
                if scored:
                    self._buffer.add(scored)
                for i, p in enumerate(pending):
                    self._refill(i, cfg.rollout_inflight - p)
            except Exception as e:
                if self._stop.is_set():
                    return
                # a dead worker or a flaky poll must be VISIBLE, and must
                # not kill the loop that would otherwise starve training
                warn_throttled("rlhf poll loop", e)
            self._stop.wait(cfg.poll_interval_s)

    # -- learner side (caller thread) ---------------------------------------

    def train_step(self) -> dict:
        """One async iteration: consume a batch (blocking until staged),
        gate staleness, update, publish version+1, fan out. Generation
        continues throughout on the rollout actors."""
        cfg = self.config
        m = rlhf_metrics()
        t0 = time.perf_counter()
        trajs = self._buffer.take(cfg.train_batch, timeout=cfg.batch_timeout_s)
        if not trajs:
            return {"skipped": True, "reason": "no trajectories staged",
                    "weights_version": self._version}
        ages = [self._version - (t["weights_version"] or 0) for t in trajs]
        weights = staleness_weights(
            ages, cfg.max_staleness, cfg.staleness_mode, cfg.staleness_halflife
        )
        kept = [(t, w, a) for t, w, a in zip(trajs, weights, ages) if w > 0]
        dropped = len(trajs) - len(kept)
        if dropped:
            m["stale_dropped"].inc(dropped)
        if not kept:
            m["staleness"].set(float(np.mean(ages)))
            return {"skipped": True, "reason": "all trajectories stale",
                    "dropped_stale": dropped, "weights_version": self._version}
        mean_age = float(np.mean([a for _, _, a in kept]))
        m["staleness"].set(mean_age)
        self._last_batch_versions = [
            t["weights_version"] or 0 for t, _, _ in kept
        ]

        rewards = np.asarray([t["reward"] for t, _, _ in kept], np.float32)
        m["reward"].set(float(rewards.mean()))
        batch = self._build_batch(kept, group_advantages(rewards))
        metrics = self.learner_group.update(batch)
        self._version += 1
        m["learner_steps"].inc()

        # publish + fan out WITHOUT waiting (overlap contract); settled
        # acks are reaped non-blockingly, the backlog cap bounds the rest
        self._harvest_acks()
        update = publish_weights(
            self.learner_group.get_weights(), self._version,
            chunk_bytes=cfg.chunk_bytes,
        )
        self._pending_acks.append(
            (self._version, self.rollouts.push_weights(update))
        )
        if len(self._pending_acks) > 4:
            # a dead worker's ack never resolves; dropping the oldest
            # bounds the debt (the push itself is idempotent per version
            # and the next one supersedes it) — visibly, not silently
            stale_v, _ = self._pending_acks.pop(0)
            warn_throttled(
                "rlhf sync ack backlog",
                RuntimeError(f"dropping unharvested ack for v{stale_v}"),
            )

        out = {
            "weights_version": self._version,
            "mean_reward": float(rewards.mean()),
            "trajectories": len(kept),
            "dropped_stale": dropped,
            "mean_staleness": mean_age,
            "step_s": round(time.perf_counter() - t0, 4),
            **{f"learner/{k}": v for k, v in metrics.items()},
        }
        _events.record(
            "rlhf.learner.step", version=self._version,
            trajectories=len(kept), dropped_stale=dropped,
            mean_reward=round(float(rewards.mean()), 5),
            mean_staleness=round(mean_age, 3),
            loss=round(float(metrics.get("loss", 0.0)), 6),
            step_s=out["step_s"],
        )
        return out

    def _build_batch(self, kept: list, advantages: np.ndarray) -> SampleBatch:
        """Fixed-shape (B, T/O) arrays from variable-length trajectories
        (padding keeps the learner jit at one trace)."""
        cfg = self.config
        B, T, O = len(kept), self._T, self._O
        tokens = np.zeros((B, T), np.int32)
        prompt_len = np.zeros(B, np.int32)
        out_tokens = np.zeros((B, O), np.int32)
        out_len = np.zeros(B, np.int32)
        behavior = np.zeros((B, O), np.float32)
        weight = np.zeros(B, np.float32)
        for i, (t, w, _a) in enumerate(kept):
            p, o = t["prompt"], t["tokens"][:O]
            lp = t["logprobs"][: len(o)]
            tokens[i, : len(p)] = p
            tokens[i, len(p) : len(p) + len(o)] = o
            prompt_len[i] = len(p)
            out_tokens[i, : len(o)] = o
            out_len[i] = len(o)
            behavior[i, : len(lp)] = lp
            weight[i] = w
        # a NaN behavior logprob marks a token whose sampling density is
        # UNKNOWN (failover-resumed prefix, scheduler.py contract): such
        # tokens are EXCLUDED from the loss via token_mask — zero-filling
        # alone would score them as behavior-probability 1
        token_mask = np.isfinite(behavior).astype(np.float32)
        return SampleBatch(
            tokens=tokens,
            prompt_len=prompt_len,
            out_tokens=out_tokens,
            out_len=out_len,
            behavior_logp=np.nan_to_num(behavior, nan=0.0),
            token_mask=token_mask,
            advantage=advantages.astype(np.float32),
            weight=weight,
            temperature=np.full(B, cfg.temperature, np.float32),
            top_k=np.full(B, cfg.top_k, np.int32),
            top_p=np.full(B, cfg.top_p, np.float32),
        )

    def train(self, iterations: int) -> list[dict]:
        return [self.train_step() for _ in range(iterations)]

    # -- bookkeeping --------------------------------------------------------

    @property
    def weights_version(self) -> int:
        return self._version

    def _await_acks(self, acks, version: int) -> None:
        if self.config.remote_rollouts:
            import ray_tpu

            got = ray_tpu.get(list(acks), timeout=self.config.sync_ack_timeout_s)
        else:
            got = list(acks)  # local push already applied synchronously
        for v in got:
            if v != version:
                raise RuntimeError(
                    f"worker acked weight version {v}, pushed {version}"
                )

    def stats(self) -> dict:
        return {
            "weights_version": self._version,
            "buffer": self._buffer.stats(),
            "pending_acks": len(self._pending_acks),
            "last_batch_versions": list(self._last_batch_versions),
        }

    def shutdown(self) -> None:
        self._stop.set()
        if self._poller.is_alive():
            self._poller.join(timeout=5.0)
        self.rollouts.shutdown()
        self.learner_group.shutdown()
