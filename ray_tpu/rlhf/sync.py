"""Versioned learner→engine weight sync over the object plane.

The disaggregated async-RL wiring (LlamaRL / MindSpeed RL shape): the
learner PUBLISHES a ``WeightUpdate`` — the parameter pytree flattened
and chunked through ``ray_tpu.put`` — and every rollout engine APPLIES
it between ``step()`` iterations via ``LLMEngine.update_weights``,
without draining in-flight generation. Publication and application are
deliberately decoupled:

* ``publish_weights`` runs once per learner step on the driver/learner
  side; chunking keeps each object under ``chunk_bytes`` so the shared
  store never sees one giant blob, and the SAME refs fan out to every
  engine (one serialization, N consumers — the object plane's whole
  point).
* ``apply_weight_update`` runs inside each consumer (rollout actor OR
  serve replica — ``serve.llm.LLMDeployment.update_weights`` calls this
  exact function, so raw-actor engines and serve-hosted engines share
  one code path) and is the only place that fetches the chunks.

Every trajectory an engine generates is stamped with the engine's
``weights_version`` at submit; the learner's staleness gate
(``rlhf.algorithm``) compares those stamps against its own version.

Observability: ``rlhf.sync.push`` / ``rlhf.sync.apply`` flight-recorder
events carry version + latency; ``rlhf_sync_seconds{phase=...}`` and
``rlhf_sync_bytes`` make push/apply cost visible to ``obs series``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

import numpy as np

from ray_tpu._private import events as _events
from ray_tpu.rlhf.metrics import rlhf_metrics


@dataclasses.dataclass
class WeightUpdate:
    """One published parameter version. Pickles small: the arrays live in
    the object store behind ``chunk_refs``; this manifest carries only
    the version, the tree structure, and the refs."""

    version: int
    treedef: Any                 # jax PyTreeDef (pickles)
    chunk_refs: list             # ObjectRefs, each -> list[np.ndarray]
    chunk_sizes: list            # leaves per chunk (reassembly check)
    nbytes: int
    created_t: float

    @property
    def num_leaves(self) -> int:
        return sum(self.chunk_sizes)


def publish_weights(params, version: int, chunk_bytes: int = 8 << 20) -> WeightUpdate:
    """Flatten ``params`` and put it into the object plane as ≤
    ``chunk_bytes`` chunks. ONE ``device_get`` for the whole tree (the
    learner's params are device arrays; per-leaf pulls would stall the
    XLA pipeline once per leaf), then greedy chunking in leaf order."""
    import jax
    import ray_tpu

    t0 = time.perf_counter()
    leaves, treedef = jax.tree_util.tree_flatten(params)
    host = [np.asarray(a) for a in jax.device_get(leaves)]

    chunk_refs: list = []
    chunk_sizes: list = []
    cur: list = []
    cur_bytes = 0
    total = 0
    for leaf in host:
        total += leaf.nbytes
        if cur and cur_bytes + leaf.nbytes > chunk_bytes:
            chunk_refs.append(ray_tpu.put(cur))
            chunk_sizes.append(len(cur))
            cur, cur_bytes = [], 0
        cur.append(leaf)
        cur_bytes += leaf.nbytes
    if cur:
        chunk_refs.append(ray_tpu.put(cur))
        chunk_sizes.append(len(cur))

    push_s = time.perf_counter() - t0
    m = rlhf_metrics()
    m["sync_s"].observe(push_s, tags={"phase": "push"})
    m["sync_bytes"].inc(total)
    m["version"].set(version)
    _events.record(
        "rlhf.sync.push", version=version, chunks=len(chunk_refs),
        bytes=total, push_s=round(push_s, 6),
    )
    return WeightUpdate(
        version=version, treedef=treedef, chunk_refs=chunk_refs,
        chunk_sizes=chunk_sizes, nbytes=total, created_t=time.time(),
    )


def fetch_params(update: WeightUpdate, timeout: Optional[float] = 120.0):
    """Materialize the published pytree (one batched get for all chunks)."""
    import jax
    import ray_tpu

    chunks = ray_tpu.get(list(update.chunk_refs), timeout=timeout)
    leaves: list = []
    for chunk, expect in zip(chunks, update.chunk_sizes):
        if len(chunk) != expect:
            raise ValueError(
                f"weight chunk carries {len(chunk)} leaves, manifest says "
                f"{expect} (object-plane corruption or version skew)"
            )
        leaves.extend(chunk)
    return jax.tree_util.tree_unflatten(update.treedef, leaves)


def apply_weight_update(
    engine, update, timeout: Optional[float] = 120.0
) -> int:
    """Fetch + hot-swap one engine. ``update`` is a ``WeightUpdate`` (the
    normal push path) or a ``(params, version)`` tuple (tests / local
    engines that skip the object plane). Returns the installed version;
    a version the engine already has (duplicate delivery, e.g. a retried
    push) is applied idempotently — ``LLMEngine.update_weights`` only
    rejects going BACKWARDS."""
    t0 = time.perf_counter()
    if isinstance(update, WeightUpdate):
        params, version = fetch_params(update, timeout=timeout), update.version
    else:
        params, version = update
    installed = engine.update_weights(params, version)
    apply_s = time.perf_counter() - t0
    m = rlhf_metrics()
    m["sync_s"].observe(apply_s, tags={"phase": "apply"})
    _events.record(
        "rlhf.sync.apply", version=installed,
        apply_s=round(apply_s, 6),
        in_flight=engine.stats().get("running", 0),
    )
    return installed
