"""End-to-end async RLHF smoke: ``python -m ray_tpu.rlhf.smoke``.

A tiny GPT policy trained against a synthetic reward (fraction of
generated tokens equal to a target id) for a few async iterations on
CPU. Prints ONE JSON line and exits non-zero when any of the
subsystem's contracts fails to hold live:

* ``improved``        — mean reward of the last iterations beats the
  first (the loop actually learns);
* ``overlapped``      — at least one ``rlhf.rollout.finish`` recorder
  event timestamp falls strictly BETWEEN two ``rlhf.learner.step``
  events (generation demonstrably ran while the learner trained);
* ``versions_advanced`` — late consumed batches carry non-zero
  ``weights_version`` stamps (pushes landed on live engines without a
  drain).

The CI ``rlhf-smoke`` job runs this non-blocking and uploads the
flight-recorder + OTLP postmortem on failure.
"""

from __future__ import annotations

import json
import sys
import time


TARGET = 7


def reward_fn(prompt, tokens) -> float:
    if not tokens:
        return 0.0
    return sum(1 for t in tokens if t == TARGET) / len(tokens)


def run_smoke(
    iterations: int = 12,
    num_workers: int = 2,
    train_batch: int = 16,
) -> dict:
    import ray_tpu
    from ray_tpu._private import events as _events
    from ray_tpu.llm.engine import EngineConfig
    from ray_tpu.models.gpt import GPTConfig
    from ray_tpu.rlhf import Algorithm, RLHFConfig

    cfg = RLHFConfig(
        model_cfg=GPTConfig(
            vocab_size=32, seq_len=64, d_model=32, n_layers=1, n_heads=2,
            remat=False, fused_loss=False, dtype="float32",
        ),
        engine_config=EngineConfig(
            max_slots=4, num_blocks=64, block_size=4, max_blocks_per_seq=8,
            prefill_chunk=8,
        ),
        prompts=[[1, 2, 3], [3, 2, 1], [2, 2, 2]],
        reward_fn=reward_fn,
        num_rollout_workers=num_workers,
        rollout_inflight=8,
        max_tokens=8,
        temperature=1.0,
        train_batch=train_batch,
        lr=0.1,
        max_staleness=8,
        # freshness over hoarding: generation far outpaces the learner on
        # a tiny model, and a deep buffer would feed it ancient v0 data —
        # drop-oldest at 2 batches keeps consumed staleness ~1 version
        buffer_capacity=2 * train_batch,
        seed=0,
    )
    t0 = time.time()
    ray_tpu.init(num_cpus=max(4, num_workers + 2), num_tpus=0)
    algo = Algorithm(cfg)
    try:
        iters = algo.train(iterations)
        stats = algo.stats()
    finally:
        algo.shutdown()

    real = [it for it in iters if not it.get("skipped")]
    rewards = [it["mean_reward"] for it in real]
    first = rewards[0] if rewards else 0.0
    tail = rewards[-3:] if len(rewards) >= 3 else rewards
    improved = bool(tail) and (sum(tail) / len(tail)) > first

    evs = _events.snapshot()
    finishes = [e["ts"] for e in evs if e["type"] == "rlhf.rollout.finish"]
    steps = sorted(e["ts"] for e in evs if e["type"] == "rlhf.learner.step")
    overlapped = (
        len(steps) >= 2
        and any(steps[0] < ts < steps[-1] for ts in finishes)
    )
    versions_advanced = any(v > 0 for v in stats["last_batch_versions"])

    ray_tpu.shutdown()
    return {
        "metric": "rlhf_async_smoke",
        "iterations": len(real),
        "reward_first": round(first, 4),
        "reward_last": round(rewards[-1], 4) if rewards else 0.0,
        "reward_tail_mean": round(sum(tail) / len(tail), 4) if tail else 0.0,
        "improved": improved,
        "overlapped": overlapped,
        "versions_advanced": versions_advanced,
        "final_weights_version": stats["weights_version"],
        "wall_s": round(time.time() - t0, 1),
        "ok": improved and overlapped and versions_advanced,
    }


def main() -> int:
    rec = run_smoke()
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
