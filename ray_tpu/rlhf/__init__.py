"""ray_tpu.rlhf: disaggregated async RL-on-LLM.

The flagship end-to-end scenario the ROADMAP asked for: the
``ray_tpu.llm`` continuous-batching engine becomes the ROLLOUT backend
of an RL loop whose learner lives in the ``ray_tpu.rl``/``train``
machinery — generation and learning on separate resources, weight sync
overlapped with generation, staleness-corrected learning (LlamaRL
arXiv:2505.24034, MindSpeed RL arXiv:2507.19017 shapes).

    from ray_tpu import rlhf

    algo = rlhf.Algorithm(rlhf.RLHFConfig(
        model_cfg=tiny_gpt_cfg,
        prompts=[[1, 2, 3]],
        reward_fn=lambda prompt, tokens: tokens.count(7) / len(tokens),
        num_rollout_workers=2,
        temperature=1.0,
    ))
    for it in algo.train(10):
        print(it["weights_version"], it["mean_reward"])
    algo.shutdown()

Pieces (each its own module doc):

* ``rollout``   — actor-hosted engine replicas generating continuously,
  per-token behavior-logprob capture, version-stamped trajectories;
* ``sync``      — versioned weight publication (chunked object-plane
  puts) + between-step engine hot-swap, one code path shared with
  ``serve.llm.LLMDeployment.update_weights``;
* ``learner``   — GPT policy + PPO/GRPO clipped surrogate with exact
  importance correction, hosted in ``rl.learner.LearnerGroup``;
* ``algorithm`` — the async driver, the staleness admission gate, and
  the pure correction math;
* ``buffer``    — the bounded staging buffer between the two planes;
* ``metrics``   — the ``rlhf_*`` metric family (the staleness gauge
  feeds the ``rlhf-staleness`` default SLO rule).

Observability: ``rlhf.rollout.submit/finish``, ``rlhf.sync.push/apply``,
``rlhf.learner.step`` flight-recorder events; ``python -m
ray_tpu.rlhf.smoke`` runs the tiny-model async loop end to end (the CI
``rlhf-smoke`` job).
"""

from ray_tpu.rlhf.algorithm import (  # noqa: F401
    Algorithm,
    RLHFConfig,
    group_advantages,
    importance_ratios,
    staleness_weights,
)
from ray_tpu.rlhf.buffer import TrajectoryBuffer  # noqa: F401
from ray_tpu.rlhf.learner import (  # noqa: F401
    GPTPolicyModule,
    make_learner_group,
    rlhf_loss,
)
from ray_tpu.rlhf.metrics import rlhf_metrics  # noqa: F401
from ray_tpu.rlhf.rollout import RolloutGroup, RolloutWorker  # noqa: F401
from ray_tpu.rlhf.sync import (  # noqa: F401
    WeightUpdate,
    apply_weight_update,
    fetch_params,
    publish_weights,
)
