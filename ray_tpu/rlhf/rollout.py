"""Engine-backed rollout plane: actor-hosted ``LLMEngine`` replicas that
generate trajectories continuously.

Disaggregation shape (LlamaRL / MindSpeed RL): generation and learning
run on SEPARATE resources. Each ``RolloutWorker`` is an actor process
owning one continuous-batching ``LLMEngine`` whose step loop runs in a
daemon thread — exactly a serve replica minus HTTP. Every actor-facing
method is QUICK (submit/poll/update_weights touch queues and swap
pointers); the engine thread does the heavy work, so a weight push never
waits behind a long generation and the driver's poll cadence never
stalls generation.

Trajectory contract (what ``poll`` returns per finished request):

* ``tokens`` — the generated ids;
* ``logprobs`` — per-token BEHAVIOR logprobs captured at sample time
  (``models.sampling`` logprob convention) — the denominator of the
  learner's importance ratio, exact regardless of how many weight swaps
  happened mid-trajectory;
* ``weights_version`` — the engine's policy version at submit, the
  staleness gate's input;
* ``finish_reason`` / ``gen_s`` — bookkeeping.

``RolloutGroup`` is the driver-side handle: spawns N workers, fans
submits round-robin, harvests finished trajectories, and fans
``WeightUpdate`` pushes (the same chunk refs to every worker — one
serialization, N consumers).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from ray_tpu._private import events as _events
from ray_tpu.rlhf.metrics import rlhf_metrics


class RolloutWorker:
    """One rollout engine; host in an actor via ``RolloutGroup`` (or use
    in-process for tests). ``sample_seed_base`` offsets the per-request
    sampling seeds so distinct workers explore distinct trajectories
    while staying fully deterministic."""

    def __init__(
        self,
        model: str = "gpt",
        model_cfg=None,
        engine_config=None,
        seed: int = 0,
        params: Optional[dict] = None,
        sample_seed_base: int = 0,
        warmup: bool = True,
    ):
        from ray_tpu.llm.engine import LLMEngine
        from ray_tpu.serve.llm import _build_model

        cfg, params = _build_model(model, model_cfg, params, seed)
        self._engine = LLMEngine(cfg, params, engine_config)
        if warmup:
            self._engine.warmup()
        self._seed_base = int(sample_seed_base)
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._pending: list[tuple] = []  # (Request, prompt, submit_t)
        self._stop = threading.Event()
        self._loop = threading.Thread(
            target=self._engine.run_loop, args=(self._stop,),
            name="rlhf-rollout-loop", daemon=True,
        )
        self._loop.start()

    # -- data plane (all quick: the engine thread does the real work) ------

    def submit(
        self,
        prompts: list,
        max_tokens: int = 16,
        temperature: float = 1.0,
        top_k: int = 0,
        top_p: float = 1.0,
    ) -> int:
        """Queue prompts for generation; returns the worker's pending
        count AFTER the submit (driver-side refill accounting)."""
        from ray_tpu.llm.scheduler import SamplingParams

        if not self._loop.is_alive():
            raise RuntimeError("rollout engine loop thread died")
        now = time.time()
        with self._lock:
            for prompt in prompts:
                params = SamplingParams(
                    max_tokens=max_tokens,
                    temperature=temperature,
                    top_k=top_k,
                    top_p=top_p,
                    seed=self._seed_base + next(self._seq),
                )
                req = self._engine.submit([int(t) for t in prompt], params)
                self._pending.append((req, list(prompt), now))
            return len(self._pending)

    def poll(self) -> dict:
        """Harvest finished trajectories: ``{"trajs": [...], "pending": n}``."""
        now = time.time()
        with self._lock:
            done = [p for p in self._pending if p[0].finished]
            self._pending = [p for p in self._pending if not p[0].finished]
            pending = len(self._pending)
        trajs = [
            {
                "prompt": prompt,
                "tokens": list(req.out),
                "logprobs": list(req.out_logprobs),
                "weights_version": req.weights_version,
                "finish_reason": req.finish_reason,
                "gen_s": now - t0,
            }
            for req, prompt, t0 in done
        ]
        return {"trajs": trajs, "pending": pending}

    # -- control plane -----------------------------------------------------

    def update_weights(self, update, timeout: float = 120.0) -> int:
        """Apply a published ``WeightUpdate`` (or ``(params, version)``)
        between engine steps — in-flight generation keeps running
        (``LLMEngine.update_weights``)."""
        from ray_tpu.rlhf.sync import apply_weight_update

        return apply_weight_update(self._engine, update, timeout=timeout)

    def weights_version(self) -> int:
        return self._engine.weights_version

    def stats(self) -> dict:
        s = self._engine.stats()
        with self._lock:
            s["rollout_pending"] = len(self._pending)
        return s

    def check_health(self) -> None:
        if not self._loop.is_alive():
            raise RuntimeError("rollout engine loop thread died")

    def stop(self) -> bool:
        self._stop.set()
        return True


class RolloutGroup:
    """Driver-side handle over N actor-hosted rollout workers.

    ``remote=False`` keeps a single in-process worker (unit tests, and
    debugging without a cluster); the API is identical.
    """

    def __init__(
        self,
        num_workers: int = 1,
        worker_kwargs: Optional[dict] = None,
        remote: bool = True,
        num_cpus: float = 1,
    ):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        kwargs = dict(worker_kwargs or {})
        self._remote = remote
        self._rr = 0
        self._workers: list = []
        if remote:
            import ray_tpu

            cls = ray_tpu.remote(RolloutWorker)
            for i in range(num_workers):
                wk = dict(kwargs)
                # disjoint seed lanes per worker: deterministic yet diverse
                wk["sample_seed_base"] = (
                    kwargs.get("sample_seed_base", 0) + i * 1_000_003
                )
                self._workers.append(
                    cls.options(num_cpus=num_cpus).remote(**wk)
                )
        else:
            if num_workers != 1:
                raise ValueError("remote=False supports a single worker")
            self._workers.append(RolloutWorker(**kwargs))

    @property
    def num_workers(self) -> int:
        return len(self._workers)

    def submit(self, prompts: list, timeout: float = 60.0, **sampling) -> int:
        """Round-robin one batch of prompts onto the next worker; returns
        that worker's resulting pending count."""
        w = self._workers[self._rr % len(self._workers)]
        self._rr += 1
        _events.record(
            "rlhf.rollout.submit", n=len(prompts),
            worker=(self._rr - 1) % len(self._workers),
        )
        if not self._remote:
            return w.submit(prompts, **sampling)
        import ray_tpu

        return ray_tpu.get(w.submit.remote(prompts, **sampling), timeout=timeout)

    def submit_to(self, idx: int, prompts: list, timeout: float = 60.0, **sampling) -> int:
        """Targeted submit (the refill loop keeps EVERY worker saturated,
        which round-robin alone cannot when workers drain unevenly)."""
        w = self._workers[idx]
        _events.record("rlhf.rollout.submit", n=len(prompts), worker=idx)
        if not self._remote:
            return w.submit(prompts, **sampling)
        import ray_tpu

        return ray_tpu.get(w.submit.remote(prompts, **sampling), timeout=timeout)

    def poll(self, timeout: float = 60.0) -> tuple[list[dict], list[int]]:
        """Harvest every worker once: (trajectories, per-worker pending).
        Each harvested trajectory records an ``rlhf.rollout.finish`` event
        in the DRIVER's ring (the overlap proof the smoke test reads) and
        counts into ``rlhf_rollout_tokens``."""
        if self._remote:
            import ray_tpu

            outs = ray_tpu.get(
                [w.poll.remote() for w in self._workers], timeout=timeout
            )
        else:
            outs = [w.poll() for w in self._workers]
        trajs: list[dict] = []
        pending: list[int] = []
        for i, out in enumerate(outs):
            pending.append(out["pending"])
            for t in out["trajs"]:
                t["worker"] = i
                trajs.append(t)
        if trajs:
            m = rlhf_metrics()
            m["rollout_trajs"].inc(len(trajs))
            m["rollout_tokens"].inc(sum(len(t["tokens"]) for t in trajs))
            for t in trajs:
                _events.record(
                    "rlhf.rollout.finish", worker=t["worker"],
                    tokens=len(t["tokens"]),
                    weights_version=t["weights_version"],
                    reason=t["finish_reason"], gen_s=round(t["gen_s"], 4),
                )
        return trajs, pending

    def push_weights(self, update) -> list:
        """Fan one ``WeightUpdate`` to every worker WITHOUT waiting —
        returns the ack refs (version numbers) so the caller can harvest
        them later; generation never drains (``rlhf.sync`` module doc)."""
        if not self._remote:
            return [self._workers[0].update_weights(update)]
        return [w.update_weights.remote(update) for w in self._workers]

    def versions(self, timeout: float = 30.0) -> list[int]:
        if not self._remote:
            return [self._workers[0].weights_version()]
        import ray_tpu

        return ray_tpu.get(
            [w.weights_version.remote() for w in self._workers], timeout=timeout
        )

    def shutdown(self) -> None:
        import ray_tpu

        from ray_tpu._private.log_util import warn_throttled

        for w in self._workers:
            try:
                if self._remote:
                    ray_tpu.kill(w)
                else:
                    w.stop()
            except Exception as e:
                # best-effort teardown, but never silent: a leaked rollout
                # actor keeps generating against dead weights forever
                warn_throttled("rlhf rollout group teardown", e)
