"""The learning half of the async RLHF loop: a GPT policy hosted in the
existing ``rl.learner`` machinery.

Reuse, not reinvention: ``rl.learner.Learner`` already owns the
optimizer, grad clipping, device-mesh data parallelism, and the
local-vs-remote-actor placement (``LearnerGroup``). This module only
supplies what RL-on-LLM changes — the module (a decoder-only GPT whose
``init`` is exactly the rollout engines' init, so version 0 means the
same weights everywhere) and the loss (a PPO/GRPO-style clipped
surrogate over TOKENS with off-policy importance correction).

The correction is the heart of the async design: trajectories were
sampled by engines running version ``v_behind``, the learner is at
``v_now``. Each token carries the behavior logprob captured AT SAMPLE
TIME (``models.sampling`` logprob convention), the loss recomputes the
current-policy logprob of the same token with ``token_logprobs`` under
the SAME sampling knobs, and ``ratio = exp(cur - behavior)`` is then an
exact density ratio — clipped a la PPO so a very-stale trajectory can
pull, not yank. The staleness gate (``rlhf.algorithm``) additionally
drops/down-weights whole trajectories via ``batch["weight"]``.

Batch layout (all fixed shapes — the update jits once):

* ``tokens``        (B, T) int32 — prompt + generated, right-padded
* ``prompt_len``    (B,)  int32
* ``out_tokens``    (B, O) int32 — generated ids, right-padded
* ``out_len``       (B,)  int32
* ``behavior_logp`` (B, O) float32
* ``token_mask``    (B, O) float32 — 0 where the behavior density is
  unknown (failover-resumed tokens; excluded from the loss entirely)
* ``advantage``     (B,)  float32 — group-relative (GRPO) advantage
* ``weight``        (B,)  float32 — staleness gate output (0 = masked)
* ``temperature``/``top_k``/``top_p`` (B,) — the rollout's knobs
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init
from ray_tpu.models.sampling import token_logprobs
from ray_tpu.rl.learner import LearnerGroup


class GPTPolicyModule:
    """Adapter giving ``rl.learner.Learner`` the two hooks it needs.
    ``init`` delegates to ``gpt_init`` — the same function rollout
    engines use (``serve.llm._build_model``), so a learner and a worker
    seeded alike start bit-identical at version 0."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init(self, rng):
        return gpt_init(rng, self.cfg)


def rlhf_loss(clip_param: float = 0.2, kl_coeff: float = 0.0):
    """Token-level clipped surrogate with importance correction.

    ``advantage`` is per-trajectory (GRPO: reward standardized within
    the consumed batch — no value net), broadcast over that trajectory's
    tokens. ``kl_coeff > 0`` adds the standard approximate-KL penalty
    ``E[behavior_logp - cur_logp]`` pulling the policy back toward the
    behavior distribution.
    """

    def loss_fn(module: GPTPolicyModule, params, batch):
        tokens = batch["tokens"].astype(jnp.int32)
        B, T = tokens.shape
        O = batch["out_tokens"].shape[1]
        logits = gpt_forward(module.cfg, params, tokens)  # (B, T, V)
        # position prompt_len-1+j predicts generated token j
        idx = batch["prompt_len"].astype(jnp.int32)[:, None] - 1 + jnp.arange(
            O, dtype=jnp.int32
        )[None, :]
        idx = jnp.clip(idx, 0, T - 1)
        pos_logits = jnp.take_along_axis(logits, idx[:, :, None], axis=1)
        V = pos_logits.shape[-1]

        rep = lambda x: jnp.repeat(x.astype(jnp.float32), O)
        cur_lp = token_logprobs(
            pos_logits.reshape(B * O, V),
            batch["out_tokens"].reshape(B * O).astype(jnp.int32),
            rep(batch["temperature"]),
            jnp.repeat(batch["top_k"].astype(jnp.int32), O),
            rep(batch["top_p"]),
        ).reshape(B, O)

        mask = (
            jnp.arange(O, dtype=jnp.int32)[None, :]
            < batch["out_len"].astype(jnp.int32)[:, None]
        ).astype(jnp.float32)
        # token_mask zeroes positions whose behavior density is UNKNOWN
        # (failover-resumed tokens carry NaN logprobs — they must be
        # excluded, not scored as probability 1)
        mask = mask * batch["token_mask"].astype(jnp.float32)
        w = batch["weight"].astype(jnp.float32)[:, None] * mask
        denom = jnp.maximum(w.sum(), 1.0)

        log_ratio = cur_lp - batch["behavior_logp"]
        ratio = jnp.exp(log_ratio)
        adv = batch["advantage"].astype(jnp.float32)[:, None]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip_param, 1.0 + clip_param) * adv,
        )
        pi_loss = -(surr * w).sum() / denom
        # KL in clamped log space: a behavior token the CURRENT filter
        # masks out scores ~-1e30 (token_logprobs doc) — correct for the
        # ratio (exp -> 0, clipped) but it would blow the log-space KL
        # term (and a kl_coeff-weighted loss) to ~1e30 from one token
        approx_kl = -(jnp.clip(log_ratio, -20.0, 20.0) * w).sum() / denom
        clip_frac = ((jnp.abs(ratio - 1.0) > clip_param) * w).sum() / denom
        total = pi_loss + kl_coeff * approx_kl
        return total, {
            "policy_loss": pi_loss,
            "kl": approx_kl,
            "mean_ratio": (ratio * w).sum() / denom,
            "clip_frac": clip_frac,
        }

    return loss_fn


def make_learner_group(
    model_cfg: GPTConfig,
    lr: float = 1e-2,
    grad_clip: Optional[float] = 1.0,
    clip_param: float = 0.2,
    kl_coeff: float = 0.0,
    seed: int = 0,
    remote: bool = False,
) -> LearnerGroup:
    """The async loop's learner: GPT policy + rlhf loss in the shared
    ``rl.learner`` machinery (``remote=True`` places it in its own actor
    so the update stream never contends with the driver's poll loop)."""
    return LearnerGroup(
        dict(
            module_factory=lambda: GPTPolicyModule(model_cfg),
            loss_fn=rlhf_loss(clip_param, kl_coeff),
            lr=lr,
            grad_clip=grad_clip,
            seed=seed,
        ),
        remote=remote,
    )
