"""User-visible exception types.

Mirrors the reference's ``python/ray/exceptions.py`` surface (RayError,
RayTaskError, RayActorError, GetTimeoutError, ObjectLostError,
TaskCancelledError, ...) so users migrating from the reference find the same
failure taxonomy.
"""

from __future__ import annotations

import traceback


class RayError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayError):
    """Wraps an exception raised inside a remote task/actor method.

    Like the reference (``python/ray/exceptions.py`` RayTaskError), getting an
    object whose producing task failed re-raises the error on the caller, with
    the remote traceback attached, and the error propagates through dependent
    tasks.
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"{function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        return (RayTaskError, (self.function_name, self.traceback_str, self.cause))

    @classmethod
    def from_exception(cls, function_name: str, exc: Exception) -> "RayTaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        try:  # keep the original exception if it is picklable
            import cloudpickle

            cloudpickle.dumps(exc)
            cause = exc
        except Exception:
            cause = RayError(repr(exc))
        return cls(function_name, tb, cause)

    def as_instanceof_cause(self) -> Exception:
        """Return an exception that is also an instance of the cause's type so
        ``except UserError`` works across the task boundary."""
        cause = self.cause
        if isinstance(cause, RayTaskError):
            return cause.as_instanceof_cause()
        cls = type(cause)
        if cls in (RayError,) or issubclass(cls, RayTaskError):
            return self
        try:
            derived = type(
                "RayTaskError(" + cls.__name__ + ")",
                (RayTaskError, cls),
                {"__init__": lambda s: None},
            )()
            derived.__dict__.update(self.__dict__)
            derived.args = self.args
            return derived
        except TypeError:
            return self


class RayActorError(RayError):
    """The actor died before or during this method call."""

    def __init__(self, actor_id=None, msg="The actor died unexpectedly before finishing this task."):
        self.actor_id = actor_id
        super().__init__(msg)


class ActorDiedError(RayActorError):
    pass


class ActorUnavailableError(RayActorError):
    """The actor is temporarily unavailable (e.g. restarting)."""


class GetTimeoutError(RayError, TimeoutError):
    pass


class ObjectLostError(RayError):
    def __init__(self, object_id_hex: str, msg: str | None = None):
        self.object_id_hex = object_id_hex
        super().__init__(msg or f"Object {object_id_hex} was lost (node died) and could not be reconstructed.")


class ObjectStoreFullError(RayError):
    pass


class TaskCancelledError(RayError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__("This task or its dependency was cancelled")


class WorkerCrashedError(RayError):
    def __init__(self, msg="The worker died unexpectedly while executing this task."):
        super().__init__(msg)


class RuntimeEnvSetupError(RayError):
    pass


class PlacementGroupSchedulingError(RayError):
    pass


class OutOfMemoryError(RayError):
    """The memory monitor killed this task's worker to relieve host memory
    pressure (reference: ``worker_killing_policy.h`` + OOM-killed task
    errors)."""


class OverloadedError(RayError):
    """The request was shed by deadline-aware admission control instead of
    queued as doomed work (RESILIENCE.md): the serving engine's backlog ÷
    service rate said the deadline could not be met.  The serve HTTP proxy
    maps this to ``429 Too Many Requests`` with a ``Retry-After`` header
    from ``retry_after_s``."""

    def __init__(self, msg: str = "server overloaded", retry_after_s: float = 1.0):
        self.retry_after_s = retry_after_s
        super().__init__(msg)

    def __reduce__(self):
        return (OverloadedError, (self.args[0] if self.args else "", self.retry_after_s))
