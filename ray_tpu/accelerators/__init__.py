from ray_tpu.accelerators import tpu  # noqa: F401
