"""TPU topology detection and resource synthesis.

Counterpart of the reference's ``python/ray/_private/accelerators/tpu.py``
(GKE/GCE metadata probing :14-28, ``TPU_VISIBLE_CHIPS`` :30, pod detection,
``TPU-{version}-{pod}-head`` resource synthesis) — but TPU-first: here the
chip is the *primary* accelerator, and slice topology (hosts × chips, ICI
domain) is what placement groups reserve.

Detection never imports jax eagerly (worker spawn must stay light); it probes,
in order: ``RAY_TPU_CHIPS`` env, ``TPU_VISIBLE_CHIPS``/``TPU_CHIPS_PER_HOST``,
GCE metadata env mirrors (``TPU_ACCELERATOR_TYPE``), and finally jax if (and
only if) it is already imported in this process.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Optional

# chips per host for each accelerator generation (v4/v5p: 4 chips/host;
# v5e/v6e: up to 8)
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 8, "v5e": 8, "v6e": 8}


def accelerator_type() -> Optional[str]:
    """e.g. 'v5litepod-256' / 'v5e-8' from env (GCE metadata mirror)."""
    for var in ("TPU_ACCELERATOR_TYPE", "RAY_TPU_ACCELERATOR_TYPE"):
        v = os.environ.get(var)
        if v:
            return v
    return None


def parse_accelerator_type(acc: str) -> tuple[str, int]:
    """'v5litepod-256' -> ('v5litepod', 256 chips in the pod slice)."""
    m = re.match(r"(v\d+[a-z]*)-(\d+)", acc)
    if not m:
        raise ValueError(f"Unrecognized TPU accelerator type {acc!r}")
    return m.group(1), int(m.group(2))


def detect_num_chips() -> int:
    """Number of TPU chips attached to *this host*."""
    env = os.environ.get("RAY_TPU_CHIPS") or os.environ.get("TPU_CHIPS_PER_HOST")
    if env:
        return int(env)
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    acc = accelerator_type()
    if acc:
        gen, pod_chips = parse_accelerator_type(acc)
        return min(pod_chips, _CHIPS_PER_HOST.get(gen, 4))
    # Only consult jax if something else in the process already paid its
    # import cost (drivers typically have; fresh workers have not).
    if "jax" in sys.modules:
        try:
            import jax

            return len([d for d in jax.local_devices() if d.platform in ("tpu", "axon")])
        except Exception:
            return 0
    return 0


def extra_resources(num_chips: int) -> dict[str, float]:
    """Synthesized resources for slice-aware scheduling, mirroring the
    reference's ``TPU-{version}-{pod}-head`` trick: the first host of a pod
    slice exposes a head resource so exactly one actor can claim slice
    leadership, and every host exposes an accelerator-type resource for
    affinity."""
    out: dict[str, float] = {}
    acc = accelerator_type()
    if acc:
        out[f"TPU-{acc}"] = float(num_chips)
        worker_id = int(os.environ.get("TPU_WORKER_ID", "0") or 0)
        if worker_id == 0:
            out[f"TPU-{acc}-head"] = 1.0
    return out


def slice_hosts(acc: str) -> int:
    """Hosts in a slice of the given accelerator type."""
    gen, pod_chips = parse_accelerator_type(acc)
    per_host = _CHIPS_PER_HOST.get(gen, 4)
    return max(1, pod_chips // per_host)
