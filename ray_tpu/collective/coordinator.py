"""Rendezvous + host-side collective coordinator actor.

The reference rendezvouses NCCL communicators through a named actor holding
the unique id (``util/collective/collective_group/nccl_collective_group.py:
28-77`` NCCLUniqueIDStore); data then flows over NCCL. On TPU the *device*
tensor plane is compiled XLA collectives over ICI — host-side collectives
(small CPU tensors, control data) flow through this named coordinator actor
instead, riding the shared-memory object plane.

One coordinator actor per group, named ``collective://<group>``. All methods
are non-blocking (the actor single-threads them); members poll ``try_*``
methods. Sequence numbers order successive collectives on the same group.
"""

from __future__ import annotations

import time
from typing import Any, Optional


class CollectiveCoordinator:
    """State machine for one collective group's host-side ops."""

    def __init__(self, group_name: str, world_size: int):
        self.group_name = group_name
        self.world_size = world_size
        self.joined: set[int] = set()
        # (kind, seq) -> {"parts": {rank: payload}, "result": Any, "taken": set}
        self.slots: dict[tuple, dict] = {}
        # point-to-point mailboxes: (src, dst, seq) -> payload
        self.mail: dict[tuple, Any] = {}

    def join(self, rank: int) -> int:
        self.joined.add(rank)
        return self.world_size

    def ready(self) -> bool:
        return len(self.joined) >= self.world_size

    # ------------------------------------------------------------- fan-in ops

    def _slot(self, key: tuple) -> dict:
        s = self.slots.get(key)
        if s is None:
            s = self.slots[key] = {"parts": {}, "result": None, "taken": set()}
        return s

    def put_part(self, kind: str, seq: int, rank: int, payload) -> None:
        self._slot((kind, seq))["parts"][rank] = payload

    def try_collect(self, kind: str, seq: int, rank: int, op: Optional[str] = None):
        """Returns ``(True, result)`` once all ranks contributed, else
        ``(False, None)``. The result is computed once and cached; the slot is
        freed when every rank has taken it."""
        key = (kind, seq)
        s = self.slots.get(key)
        if s is None or len(s["parts"]) < self.world_size:
            return False, None
        if s["result"] is None:
            s["result"] = self._reduce(kind, s["parts"], op)
        s["taken"].add(rank)
        result = s["result"]
        if len(s["taken"]) >= self.world_size:
            del self.slots[key]
        return True, result

    def _reduce(self, kind: str, parts: dict[int, Any], op: Optional[str]):
        from ray_tpu.collective.types import ReduceOp

        ordered = [parts[r] for r in range(self.world_size)]
        if kind == "allgather":
            return ordered
        if kind == "barrier":
            return True
        if kind in ("allreduce", "reducescatter"):
            rop = ReduceOp(op or "sum")
            acc = ordered[0]
            for p in ordered[1:]:
                acc = rop.combine(acc, p)
            if kind == "reducescatter":
                import numpy as np

                return np.array_split(np.asarray(acc), self.world_size)
            return acc
        raise ValueError(f"unknown collective kind {kind!r}")

    # ----------------------------------------------------------- broadcast

    def bcast_put(self, seq: int, payload) -> None:
        self._slot(("broadcast", seq))["result"] = payload

    def bcast_try_get(self, seq: int, rank: int):
        key = ("broadcast", seq)
        s = self.slots.get(key)
        if s is None or s["result"] is None:
            return False, None
        s["taken"].add(rank)
        result = s["result"]
        if len(s["taken"]) >= self.world_size - 1:  # root doesn't fetch
            del self.slots[key]
        return True, result

    # -------------------------------------------------------- point-to-point

    def p2p_put(self, src: int, dst: int, seq: int, payload) -> None:
        self.mail[(src, dst, seq)] = payload

    def p2p_try_get(self, src: int, dst: int, seq: int):
        key = (src, dst, seq)
        if key in self.mail:
            return True, self.mail.pop(key)
        return False, None


def poll(fn, timeout: float = 60.0, interval: float = 0.002):
    """Client-side poll helper: call ``fn()`` (returning (done, value)) until
    done or timeout."""
    deadline = time.monotonic() + timeout
    while True:
        done, value = fn()
        if done:
            return value
        if time.monotonic() > deadline:
            raise TimeoutError("collective operation timed out")
        time.sleep(interval)
        interval = min(interval * 1.5, 0.05)
