"""Rendezvous + host-side collective coordinator (EVENT-driven).

The reference rendezvouses NCCL communicators through a named actor holding
the unique id (``util/collective/collective_group/nccl_collective_group.py:
28-77`` NCCLUniqueIDStore) and moves host-side payloads over gloo
(``gloo_collective_group.py``). On TPU the *device* tensor plane is compiled
XLA collectives over ICI; host-side collectives flow through this named
ASYNC actor instead.

Round 2 had members busy-polling ``try_*`` methods every 2ms and funneling
every byte through the coordinator. Now:

* every operation is one BLOCKING call on an asyncio actor — the awaiting
  side parks on an ``asyncio.Event`` and is woken by the arriving peer
  (pushed notification, zero polling anywhere);
* small payloads ride the call itself; bulk payloads travel as ObjectRefs
  whose bytes move peer-to-peer through the object plane (shm locally, the
  data plane across hosts) — the coordinator shuttles only refs, so no
  single process handles O(world) bytes (see collective._ring_allreduce).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional


class CollectiveCoordinator:
    """Async state machine for one collective group's host-side ops.

    Runs on the asyncio actor engine (single loop thread): state mutations
    are loop-serialized, waits are real ``asyncio.Event`` parks."""

    def __init__(self, group_name: str, world_size: int):
        self.group_name = group_name
        self.world_size = world_size
        self.joined: set[int] = set()
        # (kind, seq) -> {"parts": {rank: payload}, "result": Any,
        #                 "taken": set, "event": asyncio.Event}
        self.slots: dict[tuple, dict] = {}
        # arbitrary-key mailboxes: key -> payload, with a waker per key
        self.mail: dict[tuple, Any] = {}
        self._mail_events: dict[tuple, asyncio.Event] = {}
        # keys whose taker timed out: a late put is dropped, not stored
        self._mail_dead: set[tuple] = set()

    async def join(self, rank: int) -> int:
        self.joined.add(rank)
        return self.world_size

    async def ready(self) -> bool:
        return len(self.joined) >= self.world_size

    # ------------------------------------------------------------- fan-in ops

    def _slot(self, key: tuple) -> dict:
        s = self.slots.get(key)
        if s is None:
            s = self.slots[key] = {
                "parts": {},
                "result": None,
                "taken": set(),
                "event": asyncio.Event(),
            }
        return s

    async def collect(
        self, kind: str, seq: int, rank: int, payload, op: Optional[str] = None,
        timeout: float = 60.0,
    ):
        """Contribute this rank's part and block until every rank has
        contributed; returns the combined result. The last arriver computes
        the result once and wakes the rest."""
        key = (kind, seq)
        s = self._slot(key)
        s["parts"][rank] = payload
        if len(s["parts"]) >= self.world_size:
            s["result"] = self._reduce(kind, s["parts"], op)
            s["event"].set()
        else:
            try:
                await asyncio.wait_for(s["event"].wait(), timeout=timeout)
            except asyncio.TimeoutError:
                # withdraw: this rank won't take the result, and a slot of
                # orphaned payloads must not outlive the op on a DETACHED
                # actor (it would leak for the life of the cluster)
                s["parts"].pop(rank, None)
                if not s["parts"]:
                    self.slots.pop(key, None)
                raise
        s["taken"].add(rank)
        result = s["result"]
        if len(s["taken"]) >= self.world_size:
            del self.slots[key]
        return result

    def _reduce(self, kind: str, parts: dict[int, Any], op: Optional[str]):
        from ray_tpu.collective.types import ReduceOp

        ordered = [parts[r] for r in range(self.world_size)]
        if kind == "allgather":
            return ordered
        if kind in ("barrier", "ring_done"):
            return True
        if kind in ("allreduce", "reducescatter"):
            rop = ReduceOp(op or "sum")
            acc = ordered[0]
            for p in ordered[1:]:
                acc = rop.combine(acc, p)
            if kind == "reducescatter":
                import numpy as np

                return np.array_split(np.asarray(acc), self.world_size)
            return acc
        raise ValueError(f"unknown collective kind {kind!r}")

    # ------------------------------------------------------------- mailboxes

    def _mail_event(self, key: tuple) -> asyncio.Event:
        ev = self._mail_events.get(key)
        if ev is None:
            ev = self._mail_events[key] = asyncio.Event()
        return ev

    async def mail_put(self, key: tuple, payload) -> None:
        key = tuple(key)
        if key in self._mail_dead:
            # the taker already timed out and tombstoned this key: drop the
            # payload, or it (and any ObjectRef it pins) would leak on the
            # detached actor forever
            self._mail_dead.discard(key)
            return
        self.mail[key] = payload
        self._mail_event(key).set()

    async def mail_take(self, key: tuple, timeout: float = 60.0):
        key = tuple(key)
        try:
            await asyncio.wait_for(self._mail_event(key).wait(), timeout=timeout)
        except asyncio.TimeoutError:
            # nobody will ever take this mailbox: drop the event, drop any
            # payload that landed in the race, and tombstone the key so a
            # LATE put is discarded instead of recreating the entry
            self._mail_events.pop(key, None)
            self.mail.pop(key, None)
            self._mail_dead.add(key)
            while len(self._mail_dead) > 4096:
                self._mail_dead.pop()
            raise
        self._mail_events.pop(key, None)
        return self.mail.pop(key)

    # ----------------------------------------------------------- broadcast

    async def bcast(self, seq: int, rank: int, src: int, payload=None,
                    timeout: float = 60.0):
        key = ("broadcast", seq)
        s = self._slot(key)
        if rank == src:
            s["result"] = payload
            s["event"].set()
            taken_target = self.world_size - 1  # root doesn't fetch
            if len(s["taken"]) >= taken_target:
                del self.slots[key]
            return None
        try:
            await asyncio.wait_for(s["event"].wait(), timeout=timeout)
        except asyncio.TimeoutError:
            s["taken"].add(rank)  # won't fetch; let the slot drain
            if len(s["taken"]) >= self.world_size - 1:
                self.slots.pop(key, None)
            raise
        s["taken"].add(rank)
        result = s["result"]
        if len(s["taken"]) >= self.world_size - 1:
            self.slots.pop(key, None)
        return result

    # -------------------------------------------------------- point-to-point

    async def p2p_put(self, src: int, dst: int, seq: int, payload) -> None:
        await self.mail_put(("p2p", src, dst, seq), payload)

    async def p2p_get(self, src: int, dst: int, seq: int, timeout: float = 60.0):
        return await self.mail_take(("p2p", src, dst, seq), timeout=timeout)
