"""Collective types: reduce ops and group metadata.

Reference: ``python/ray/util/collective/types.py`` (ReduceOp enum, options
dataclasses). Ours is numpy/JAX-flavored: a ReduceOp maps to the numpy ufunc
used host-side and to the jax.lax collective used in compiled programs.
"""

from __future__ import annotations

import dataclasses
import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"

    def combine(self, a, b):
        import numpy as np

        if self is ReduceOp.SUM:
            return np.add(a, b)
        if self is ReduceOp.PRODUCT:
            return np.multiply(a, b)
        if self is ReduceOp.MIN:
            return np.minimum(a, b)
        return np.maximum(a, b)


@dataclasses.dataclass
class GroupInfo:
    group_name: str
    world_size: int
    rank: int
    backend: str
