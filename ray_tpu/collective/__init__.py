"""ray_tpu.collective — collectives among actors (host plane) and meshes
(device plane). See ``collective.py`` for the backend story."""

from ray_tpu.collective.collective import (  # noqa: F401
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    mesh_allreduce,
    recv,
    reducescatter,
    send,
)
from ray_tpu.collective.types import ReduceOp  # noqa: F401
