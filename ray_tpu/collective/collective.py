"""Collective communication among actors/tasks.

API parity with the reference's ``ray.util.collective``
(``util/collective/collective.py``: ``init_collective_group`` :120,
``allreduce`` :258, ``barrier`` :298, ``broadcast`` :373, ``allgather`` :423,
``reducescatter`` :472, ``send``/``recv`` :531/:594) — but the backends are
TPU-native:

* ``backend="host"`` (default): host-side CPU tensors move through a named
  coordinator actor + the shared-memory object plane. This replaces Gloo.
* Device arrays DON'T use this API on TPU: the tensor plane is XLA
  collectives (psum/all_gather/ppermute) compiled into pjit programs over the
  mesh — see ``ray_tpu.parallel``. ``mesh_allreduce`` et al. below are thin
  jitted helpers for one-off device reductions on a local mesh.

Each participating process keeps a per-group sequence counter; collectives on
a group must be called in the same order by all members (same contract as
NCCL/Gloo).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import numpy as np

from ray_tpu.collective.coordinator import CollectiveCoordinator
from ray_tpu.collective.types import GroupInfo, ReduceOp

# Process-level registry (one membership per process, like an NCCL
# communicator): any thread of a member actor may issue collectives, but
# concurrent collectives on the same group must be externally ordered.
_registry: dict[str, dict] = {}
_registry_lock = threading.Lock()


def _groups() -> dict[str, dict]:
    return _registry


def _coordinator_handle(group_name: str, world_size: int):
    import ray_tpu
    from ray_tpu.actor import get_actor

    name = f"collective://{group_name}"
    try:
        return get_actor(name)
    except ValueError:
        pass
    Coordinator = ray_tpu.remote(num_cpus=0)(CollectiveCoordinator)
    try:
        return Coordinator.options(
            name=name, lifetime="detached", get_if_exists=True
        ).remote(group_name, world_size)
    except ValueError:
        return get_actor(name)


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Join this process to a collective group (call from inside each member
    actor/task). Reference: ``collective.py:120``."""
    if rank < 0 or rank >= world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    with _registry_lock:
        if group_name in _registry:
            raise RuntimeError(f"collective group {group_name!r} already initialized")
        coord = _coordinator_handle(group_name, world_size)
        import ray_tpu

        ray_tpu.get(coord.join.remote(rank))
        _registry[group_name] = {
            "info": GroupInfo(group_name, world_size, rank, backend),
            "coord": coord,
            "seq": 0,
            "p2p_seq": {},
        }


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups()


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups().pop(group_name, None)
    if g is not None and g["info"].rank == 0:
        import ray_tpu

        try:
            ray_tpu.kill(g["coord"])
        except Exception:
            pass


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g["info"].rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g["info"].world_size if g else -1


def _group(group_name: str) -> dict:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this process; "
            f"call init_collective_group() first"
        )
    return g


def _next_seq(g: dict) -> int:
    s = g["seq"]
    g["seq"] = s + 1
    return s


def _fanin(g, kind: str, tensor, op: Optional[str], timeout: float):
    """One BLOCKING call on the async coordinator: the actor parks the call
    on an asyncio.Event until every rank contributed — pushed wakeups, no
    client-side polling anywhere (round 2 busy-polled try_* every 2ms)."""
    import ray_tpu

    seq = _next_seq(g)
    rank = g["info"].rank
    return ray_tpu.get(
        g["coord"].collect.remote(kind, seq, rank, tensor, op, timeout),
        timeout=timeout + 10.0,
    )


def _ring_threshold() -> int:
    """Tensors at or above this many bytes allreduce via the chunked ring
    (bulk bytes peer-to-peer through the object plane; the coordinator
    shuttles only refs) instead of riding the coordinator call itself.
    Tunable: ``collective_ring_threshold_bytes``."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    return GLOBAL_CONFIG.collective_ring_threshold_bytes


def _combine(a, b, opname):
    return ReduceOp(opname or "sum").combine(a, b)


def _ring_allreduce(g, arr: "np.ndarray", opname: Optional[str], timeout: float):
    """Chunked ring allreduce (reduce-scatter + allgather), the gloo/NCCL
    decomposition: each rank moves 2·(N−1)/N of the tensor, bytes flow
    rank→rank through the object plane (shm locally, data plane across
    hosts), and NO single process — coordinator included — handles O(world)
    bytes. The coordinator only forwards ObjectRefs (mail_put/mail_take)."""
    import ray_tpu

    rank, world = g["info"].rank, g["info"].world_size
    if world == 1:
        return arr.copy()
    seq = _next_seq(g)
    coord = g["coord"]
    flat = np.ascontiguousarray(arr).reshape(-1)
    # views, not copies: sends serialize them and _combine allocates fresh
    # arrays, so nothing ever mutates a chunk in place
    chunks = list(np.array_split(flat, world))
    right = (rank + 1) % world
    live_refs = []  # keep our outbound objects alive until the final barrier

    def exchange(step: int, payload) -> "np.ndarray":
        ref = ray_tpu.put(payload)
        live_refs.append(ref)
        # nest the ref in a tuple so it travels AS a ref (top-level task
        # args materialize): the coordinator never touches the bytes.
        # put and take are issued TOGETHER — the async coordinator services
        # both concurrently, halving the per-step control latency.
        p = coord.mail_put.remote(("ring", seq, step, right), (ref,))
        t = coord.mail_take.remote(("ring", seq, step, rank), timeout)
        got = ray_tpu.get(t, timeout=timeout + 10.0)
        ray_tpu.get(p, timeout=timeout)
        return ray_tpu.get(got[0], timeout=timeout)

    # phase 1: reduce-scatter — after N-1 steps, rank owns the fully reduced
    # chunk at index (rank+1) % world
    send_idx = rank
    for step in range(world - 1):
        recv_idx = (rank - 1 - step) % world
        part = exchange(step, chunks[send_idx])
        chunks[recv_idx] = _combine(chunks[recv_idx], part, opname)
        send_idx = recv_idx
    # phase 2: allgather — circulate the reduced chunks
    send_idx = (rank + 1) % world
    for step in range(world - 1):
        recv_idx = (rank - step) % world
        chunks[recv_idx] = exchange(world - 1 + step, chunks[send_idx])
        send_idx = recv_idx
    # trailing barrier: our right neighbor may not have fetched our last
    # chunk yet — don't let live_refs die under an in-flight fetch. Uses a
    # subkey of THIS op's seq (not a fresh seq): the ring consumes exactly
    # one sequence number like the direct path, so a per-rank path
    # divergence can't desynchronize the group's counters forever.
    ray_tpu.get(
        coord.collect.remote("ring_done", seq, rank, None, None, timeout),
        timeout=timeout + 10.0,
    )
    del live_refs
    return np.concatenate(chunks).reshape(arr.shape).astype(arr.dtype, copy=False)


def allreduce(tensor, group_name: str = "default", op=ReduceOp.SUM, timeout: float = 60.0):
    """All-reduce a host tensor across the group; returns the reduced array
    (and writes in place when ``tensor`` is a writable numpy array).
    Reference semantics: ``collective.py:258``; large tensors take the
    chunked ring (``_ring_allreduce``)."""
    g = _group(group_name)
    opname = op.value if isinstance(op, ReduceOp) else str(op)
    arr = np.asarray(tensor)
    if arr.nbytes >= _ring_threshold() and g["info"].world_size > 1:
        result = _ring_allreduce(g, arr, opname, timeout)
    else:
        result = _fanin(g, "allreduce", arr, opname, timeout)
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
        return tensor
    return result


def allgather(tensor, group_name: str = "default", timeout: float = 60.0) -> list:
    """Gather every rank's tensor; returns a list indexed by rank
    (reference ``collective.py:423``)."""
    g = _group(group_name)
    return _fanin(g, "allgather", np.asarray(tensor), None, timeout)


def reducescatter(tensor, group_name: str = "default", op=ReduceOp.SUM, timeout: float = 60.0):
    """Reduce across ranks, then return this rank's shard (row-split of the
    flattened leading axis; reference ``collective.py:472``)."""
    g = _group(group_name)
    opname = op.value if isinstance(op, ReduceOp) else str(op)
    shards = _fanin(g, "reducescatter", np.asarray(tensor), opname, timeout)
    return shards[g["info"].rank]


def barrier(group_name: str = "default", timeout: float = 60.0) -> None:
    """Block until every member arrives (reference ``collective.py:298``)."""
    g = _group(group_name)
    _fanin(g, "barrier", None, None, timeout)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default", timeout: float = 60.0):
    """Broadcast from ``src_rank`` to all (reference ``collective.py:373``).
    Receivers park on the coordinator's event — no polling."""
    import ray_tpu

    g = _group(group_name)
    seq = _next_seq(g)
    rank = g["info"].rank
    payload = np.asarray(tensor) if rank == src_rank else None
    result = ray_tpu.get(
        g["coord"].bcast.remote(seq, rank, src_rank, payload, timeout),
        timeout=timeout + 10.0,
    )
    if rank == src_rank:
        return tensor
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
        return tensor
    return result


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (reference ``collective.py:531``)."""
    import ray_tpu

    g = _group(group_name)
    rank = g["info"].rank
    if dst_rank == rank:
        raise ValueError("cannot send to self")
    key = (rank, dst_rank)
    seq = g["p2p_seq"].get(key, 0)
    g["p2p_seq"][key] = seq + 1
    ray_tpu.get(g["coord"].p2p_put.remote(rank, dst_rank, seq, np.asarray(tensor)))


def recv(tensor, src_rank: int, group_name: str = "default", timeout: float = 60.0):
    """Point-to-point receive; fills ``tensor`` in place when possible and
    returns the array (reference ``collective.py:594``). Blocks on the
    coordinator's mailbox event — no polling."""
    import ray_tpu

    g = _group(group_name)
    rank = g["info"].rank
    if src_rank == rank:
        raise ValueError("cannot recv from self")
    key = (src_rank, rank)
    seq = g["p2p_seq"].get(key, 0)
    g["p2p_seq"][key] = seq + 1
    result = ray_tpu.get(
        g["coord"].p2p_get.remote(src_rank, rank, seq, timeout),
        timeout=timeout + 10.0,
    )
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
        return tensor
    return result


# ---------------------------------------------------------------- device side


def mesh_allreduce(x, mesh=None, op=ReduceOp.SUM):
    """Reduce a device array across all devices of a local mesh — compiled as
    one XLA collective over ICI. For collectives *inside* a training step,
    annotate shardings and let XLA insert them (ray_tpu.parallel) instead."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from ray_tpu.parallel.mesh import make_mesh, MeshConfig

        mesh = make_mesh(MeshConfig(dp=-1, fsdp=1, tp=1, sp=1))
    op = ReduceOp(op) if not isinstance(op, ReduceOp) else op
    fns = {
        ReduceOp.SUM: jnp.sum,
        ReduceOp.PRODUCT: jnp.prod,
        ReduceOp.MIN: jnp.min,
        ReduceOp.MAX: jnp.max,
    }
    from jax.sharding import NamedSharding, PartitionSpec as P

    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    return jax.jit(lambda a: fns[op](a, axis=0))(xs)
