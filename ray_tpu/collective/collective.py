"""Collective communication among actors/tasks.

API parity with the reference's ``ray.util.collective``
(``util/collective/collective.py``: ``init_collective_group`` :120,
``allreduce`` :258, ``barrier`` :298, ``broadcast`` :373, ``allgather`` :423,
``reducescatter`` :472, ``send``/``recv`` :531/:594) — but the backends are
TPU-native:

* ``backend="host"`` (default): host-side CPU tensors move through a named
  coordinator actor + the shared-memory object plane. This replaces Gloo.
* Device arrays DON'T use this API on TPU: the tensor plane is XLA
  collectives (psum/all_gather/ppermute) compiled into pjit programs over the
  mesh — see ``ray_tpu.parallel``. ``mesh_allreduce`` et al. below are thin
  jitted helpers for one-off device reductions on a local mesh.

Each participating process keeps a per-group sequence counter; collectives on
a group must be called in the same order by all members (same contract as
NCCL/Gloo).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import numpy as np

from ray_tpu.collective.coordinator import CollectiveCoordinator, poll
from ray_tpu.collective.types import GroupInfo, ReduceOp

# Process-level registry (one membership per process, like an NCCL
# communicator): any thread of a member actor may issue collectives, but
# concurrent collectives on the same group must be externally ordered.
_registry: dict[str, dict] = {}
_registry_lock = threading.Lock()


def _groups() -> dict[str, dict]:
    return _registry


def _coordinator_handle(group_name: str, world_size: int):
    import ray_tpu
    from ray_tpu.actor import get_actor

    name = f"collective://{group_name}"
    try:
        return get_actor(name)
    except ValueError:
        pass
    Coordinator = ray_tpu.remote(num_cpus=0)(CollectiveCoordinator)
    try:
        return Coordinator.options(
            name=name, lifetime="detached", get_if_exists=True
        ).remote(group_name, world_size)
    except ValueError:
        return get_actor(name)


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Join this process to a collective group (call from inside each member
    actor/task). Reference: ``collective.py:120``."""
    if rank < 0 or rank >= world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    with _registry_lock:
        if group_name in _registry:
            raise RuntimeError(f"collective group {group_name!r} already initialized")
        coord = _coordinator_handle(group_name, world_size)
        import ray_tpu

        ray_tpu.get(coord.join.remote(rank))
        _registry[group_name] = {
            "info": GroupInfo(group_name, world_size, rank, backend),
            "coord": coord,
            "seq": 0,
            "p2p_seq": {},
        }


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups()


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups().pop(group_name, None)
    if g is not None and g["info"].rank == 0:
        import ray_tpu

        try:
            ray_tpu.kill(g["coord"])
        except Exception:
            pass


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g["info"].rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g["info"].world_size if g else -1


def _group(group_name: str) -> dict:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this process; "
            f"call init_collective_group() first"
        )
    return g


def _next_seq(g: dict) -> int:
    s = g["seq"]
    g["seq"] = s + 1
    return s


def _fanin(g, kind: str, tensor, op: Optional[str], timeout: float):
    import ray_tpu

    seq = _next_seq(g)
    rank = g["info"].rank
    coord = g["coord"]
    ray_tpu.get(coord.put_part.remote(kind, seq, rank, tensor))
    return poll(
        lambda: ray_tpu.get(coord.try_collect.remote(kind, seq, rank, op)),
        timeout=timeout,
    )


def allreduce(tensor, group_name: str = "default", op=ReduceOp.SUM, timeout: float = 60.0):
    """All-reduce a host tensor across the group; returns the reduced array
    (and writes in place when ``tensor`` is a writable numpy array).
    Reference semantics: ``collective.py:258``."""
    g = _group(group_name)
    opname = op.value if isinstance(op, ReduceOp) else str(op)
    result = _fanin(g, "allreduce", np.asarray(tensor), opname, timeout)
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
        return tensor
    return result


def allgather(tensor, group_name: str = "default", timeout: float = 60.0) -> list:
    """Gather every rank's tensor; returns a list indexed by rank
    (reference ``collective.py:423``)."""
    g = _group(group_name)
    return _fanin(g, "allgather", np.asarray(tensor), None, timeout)


def reducescatter(tensor, group_name: str = "default", op=ReduceOp.SUM, timeout: float = 60.0):
    """Reduce across ranks, then return this rank's shard (row-split of the
    flattened leading axis; reference ``collective.py:472``)."""
    g = _group(group_name)
    opname = op.value if isinstance(op, ReduceOp) else str(op)
    shards = _fanin(g, "reducescatter", np.asarray(tensor), opname, timeout)
    return shards[g["info"].rank]


def barrier(group_name: str = "default", timeout: float = 60.0) -> None:
    """Block until every member arrives (reference ``collective.py:298``)."""
    g = _group(group_name)
    _fanin(g, "barrier", None, None, timeout)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default", timeout: float = 60.0):
    """Broadcast from ``src_rank`` to all (reference ``collective.py:373``)."""
    import ray_tpu

    g = _group(group_name)
    seq = _next_seq(g)
    coord = g["coord"]
    rank = g["info"].rank
    if rank == src_rank:
        ray_tpu.get(coord.bcast_put.remote(seq, np.asarray(tensor)))
        return tensor
    result = poll(
        lambda: ray_tpu.get(coord.bcast_try_get.remote(seq, rank)), timeout=timeout
    )
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
        return tensor
    return result


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send (reference ``collective.py:531``)."""
    import ray_tpu

    g = _group(group_name)
    rank = g["info"].rank
    if dst_rank == rank:
        raise ValueError("cannot send to self")
    key = (rank, dst_rank)
    seq = g["p2p_seq"].get(key, 0)
    g["p2p_seq"][key] = seq + 1
    ray_tpu.get(g["coord"].p2p_put.remote(rank, dst_rank, seq, np.asarray(tensor)))


def recv(tensor, src_rank: int, group_name: str = "default", timeout: float = 60.0):
    """Point-to-point receive; fills ``tensor`` in place when possible and
    returns the array (reference ``collective.py:594``)."""
    import ray_tpu

    g = _group(group_name)
    rank = g["info"].rank
    if src_rank == rank:
        raise ValueError("cannot recv from self")
    key = (src_rank, rank)
    seq = g["p2p_seq"].get(key, 0)
    g["p2p_seq"][key] = seq + 1
    result = poll(
        lambda: ray_tpu.get(g["coord"].p2p_try_get.remote(src_rank, rank, seq)),
        timeout=timeout,
    )
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
        return tensor
    return result


# ---------------------------------------------------------------- device side


def mesh_allreduce(x, mesh=None, op=ReduceOp.SUM):
    """Reduce a device array across all devices of a local mesh — compiled as
    one XLA collective over ICI. For collectives *inside* a training step,
    annotate shardings and let XLA insert them (ray_tpu.parallel) instead."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        from ray_tpu.parallel.mesh import make_mesh, MeshConfig

        mesh = make_mesh(MeshConfig(dp=-1, fsdp=1, tp=1, sp=1))
    op = ReduceOp(op) if not isinstance(op, ReduceOp) else op
    fns = {
        ReduceOp.SUM: jnp.sum,
        ReduceOp.PRODUCT: jnp.prod,
        ReduceOp.MIN: jnp.min,
        ReduceOp.MAX: jnp.max,
    }
    from jax.sharding import NamedSharding, PartitionSpec as P

    xs = jax.device_put(x, NamedSharding(mesh, P(mesh.axis_names[0])))
    return jax.jit(lambda a: fns[op](a, axis=0))(xs)
