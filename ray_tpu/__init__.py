"""ray_tpu: a TPU-native distributed AI runtime.

A brand-new framework with the capabilities of the reference distributed
actor/task runtime (see SURVEY.md), re-designed TPU-first: the task/actor
core is a lean single-control-plane runtime (tasks, actors, shared-memory
objects, resource scheduling, placement groups), and the ML stack above it —
train / tune / data / serve / rl — drives JAX/XLA SPMD programs over device
meshes, with collectives compiled onto ICI instead of NCCL.

Public surface mirrors the reference's top-level API:
``init, remote, get, put, wait, kill, cancel, get_actor, method, nodes,
cluster_resources, available_resources, shutdown`` plus the subpackages
``train``, ``tune``, ``data``, ``serve``, ``rl``, ``util``, ``collective``.
"""

from ray_tpu._private.api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from ray_tpu._private.runtime import ObjectRef, ObjectRefGenerator  # noqa: F401
from ray_tpu.actor import get_actor, method  # noqa: F401
from ray_tpu import exceptions  # noqa: F401

__version__ = "0.1.0"

_LAZY_SUBMODULES = {
    "train", "tune", "data", "serve", "rl", "rlhf", "util", "collective",
    "parallel", "ops", "models", "accelerators", "cluster_utils", "dag",
    "workflow", "internal",
}


def __getattr__(name):
    # Heavy subpackages (anything touching jax) load lazily so that bare
    # runtime workers spawn fast on a 1-core host.
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f"ray_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
