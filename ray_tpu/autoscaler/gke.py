"""GKE TPU node-pool provider: the real cloud path for autoscaler v2.

Reference: ``python/ray/autoscaler/_private/gcp/node_provider.py`` (+
``config.py`` bootstrap) — the reference drives raw GCP REST through
googleapiclient with retries and operation polling. Same shape here, with
zero dependencies: :class:`GKEClient` is a thin JSON-over-urllib client
for the two API families a TPU cluster needs —

* ``container.googleapis.com``: node-pool inspection + ``setSize`` (the
  only sanctioned way to grow a GKE node pool);
* ``compute.googleapis.com``: listing a pool's VMs via its managed
  instance group and precision scale-down with
  ``instanceGroupManagers.deleteInstances`` (resize-down alone picks an
  arbitrary victim; the autoscaler must kill the IDLE one).

Auth is the GCP VM metadata server (the standard on GKE/GCE; no SDK). For
tests and air-gapped CI the transport is injectable: ``http=`` is any
``callable(method, url, body_dict|None) -> dict``.

Node identity contract: a provider node is a VM NAME. The VM's startup
script must join the cluster with ``--labels '{"provider_node_id":
"<hostname>"}'`` (``python -m ray_tpu start --address=head:port --labels
...``) — autoscaler v2 pairs cloud instances with ray nodes through that
label (``v2._reconcile_ray_nodes``), since a pool resize cannot stamp a
per-instance label ahead of time the way a direct instance insert could.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Optional

from ray_tpu.autoscaler.v2 import (
    ALLOCATED,
    ALLOCATION_FAILED,
    REQUESTED,
    AsyncNodeProvider,
    Instance,
)

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/service-accounts/default/token"
)


class _MetadataToken:
    """Bearer token from the GCE metadata server, cached until ~expiry."""

    def __init__(self):
        self._token: Optional[str] = None
        self._expires_at = 0.0

    def __call__(self) -> str:
        if self._token is None or time.time() >= self._expires_at - 60:
            req = urllib.request.Request(
                _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                payload = json.loads(resp.read().decode())
            self._token = payload["access_token"]
            self._expires_at = time.time() + float(payload.get("expires_in", 300))
        return self._token


class GKEClient:
    """Minimal GKE + Compute REST client (urllib; transport injectable)."""

    CONTAINER = "https://container.googleapis.com/v1"
    COMPUTE = "https://compute.googleapis.com/compute/v1"

    def __init__(
        self,
        project: str,
        zone: str,
        cluster: str,
        http: Optional[Callable[[str, str, Optional[dict]], dict]] = None,
        token_provider: Optional[Callable[[], str]] = None,
    ):
        self.project = project
        self.zone = zone
        self.cluster = cluster
        self._token = token_provider or _MetadataToken()
        self._http = http or self._urllib_http

    def _urllib_http(self, method: str, url: str, body: Optional[dict]) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={
                "Authorization": f"Bearer {self._token()}",
                "Content-Type": "application/json",
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                raw = resp.read().decode()
        except urllib.error.HTTPError as e:
            raise RuntimeError(
                f"GCP API {method} {url} failed: {e.code} {e.read().decode()[:500]}"
            ) from None
        except urllib.error.URLError as e:
            # connection refused / DNS / timeout — normalize so callers'
            # transient-error handling (poll keeps polling) sees one type
            raise RuntimeError(f"GCP API {method} {url} unreachable: {e}") from None
        return json.loads(raw) if raw else {}

    # -- container API ------------------------------------------------------

    def _pool_path(self, pool: str) -> str:
        return (
            f"{self.CONTAINER}/projects/{self.project}/zones/{self.zone}"
            f"/clusters/{self.cluster}/nodePools/{pool}"
        )

    def get_node_pool(self, pool: str) -> dict:
        return self._http("GET", self._pool_path(pool), None)

    def set_node_pool_size(self, pool: str, count: int) -> dict:
        return self._http(
            "POST", self._pool_path(pool) + ":setSize", {"nodeCount": int(count)}
        )

    # -- compute API (the pool's VMs live in managed instance groups) -------

    def _group_urls(self, pool: str) -> list[str]:
        return self.get_node_pool(pool).get("instanceGroupUrls", [])

    def list_pool_instances(self, pool: str) -> list[str]:
        """VM names currently in the pool's managed instance group(s)."""
        names: list[str] = []
        for group_url in self._group_urls(pool):
            # .../instanceGroupManagers/<name> — listManagedInstances works
            # on the manager resource
            out = self._http(
                "POST",
                group_url.replace("instanceGroups", "instanceGroupManagers")
                + "/listManagedInstances",
                None,
            )
            for mi in out.get("managedInstances", []):
                names.append(mi["instance"].rsplit("/", 1)[-1])
        return names

    def delete_instance(self, pool: str, name: str) -> None:
        """Precision scale-down: remove ONE named VM and shrink its group.
        Multi-location pools have one managed group per zone — the delete
        must target the group that actually CONTAINS the VM."""
        groups = self._group_urls(pool)
        if not groups:
            raise RuntimeError(f"node pool for instance {name!r} has no instance group")
        for group_url in groups:
            mgr = group_url.replace("instanceGroups", "instanceGroupManagers")
            listed = self._http("POST", mgr + "/listManagedInstances", None)
            members = {
                mi["instance"].rsplit("/", 1)[-1]: mi["instance"]
                for mi in listed.get("managedInstances", [])
            }
            if name in members:
                self._http(
                    "POST", mgr + "/deleteInstances", {"instances": [members[name]]}
                )
                return
        raise RuntimeError(f"instance {name!r} not found in node pool {pool!r}")


class GKETPUAsyncProvider(AsyncNodeProvider):
    """AsyncNodeProvider over GKE node pools of TPU hosts.

    ``pools`` maps autoscaler node-type name -> GKE node pool name; each
    create is a +1 resize of that pool, observed by polling the managed
    instance group for a VM name not seen before the request.
    """

    def __init__(
        self,
        project: str = "",
        zone: str = "",
        cluster_name: str = "",
        pools: Optional[dict[str, str]] = None,
        client: Optional[GKEClient] = None,
    ):
        self.client = client or GKEClient(project, zone, cluster_name)
        self.pools = dict(pools or {})
        # instance_id -> (pool, set of VM names preexisting at request time)
        self._pending: dict[str, tuple[str, set]] = {}
        # VM names this provider has already claimed for an instance, so two
        # concurrent creates in one pool can't both claim the same new VM
        self._claimed: set = set()
        # pool -> creates requested but not yet claimed: a resize target of
        # len(current)+1 alone is a no-op for the SECOND concurrent create
        # (real resizes are async, so the first +1 hasn't materialized yet)
        self._outstanding: dict[str, int] = {}

    def _pool_of(self, node_type: str) -> str:
        pool = self.pools.get(node_type, node_type)
        return pool

    def request_create(self, instance: Instance, resources: dict, labels: dict) -> None:
        pool = self._pool_of(instance.node_type)
        before = set(self.client.list_pool_instances(pool))
        outstanding = self._outstanding.get(pool, 0)
        self.client.set_node_pool_size(pool, len(before) + outstanding + 1)
        self._outstanding[pool] = outstanding + 1
        self._pending[instance.instance_id] = (pool, before | set(self._claimed))

    def poll(self, instance: Instance) -> str:
        rec = self._pending.get(instance.instance_id)
        if rec is None:
            return ALLOCATION_FAILED
        pool, before = rec
        try:
            now = set(self.client.list_pool_instances(pool))
        except RuntimeError:
            return REQUESTED  # transient API error: keep polling
        fresh = sorted(now - before - self._claimed)
        if not fresh:
            return REQUESTED
        name = fresh[0]
        self._claimed.add(name)
        instance.provider_id = name
        self._pending.pop(instance.instance_id, None)
        self._outstanding[pool] = max(0, self._outstanding.get(pool, 1) - 1)
        return ALLOCATED

    def terminate(self, instance: Instance) -> None:
        if not instance.provider_id:
            return
        pool = self._pool_of(instance.node_type)
        self.client.delete_instance(pool, instance.provider_id)
        self._claimed.discard(instance.provider_id)
