"""Cluster YAML: declarative launch config for ``ray_tpu up / down``.

Reference: ``python/ray/autoscaler/ray-schema.json`` + the ``ray up``
flow in ``autoscaler/_private/commands.py`` — a YAML names the provider,
the node types (shapes, labels, min/max), and head settings; ``up``
bootstraps the head and runs the autoscaler against it; ``down`` tears
every provider instance down.

Schema (validated by :func:`load_cluster_config`)::

    cluster_name: demo                  # required
    provider:                           # required
      type: gke_tpu | gce_tpu | fake    # fake = in-process virtual nodes
      project: my-project               # gke_tpu / gce_tpu
      zone: us-central2-b               # gke_tpu / gce_tpu
      cluster: my-gke-cluster           # gke_tpu only (gce_tpu creates
                                        # instances / TPU-VM nodes directly)
    head:                               # optional
      host: 127.0.0.1                   # TCP bind for agents/drivers
      port: 0                           # 0 = ephemeral
      num_cpus: 8                       # head-node CPU resource
    node_types:                         # required, at least one
      v5e-8:
        pool: v5e-pool                  # gke_tpu: node-pool name (default:
                                        # the node-type name)
        resources: {TPU: 8, CPU: 44}    # required
        labels: {accelerator: v5e}
        min_workers: 0
        max_workers: 4
    idle_timeout_s: 60                  # scale-down idle threshold
    update_interval_s: 5                # reconcile cadence

A worker VM joins with::

    python -m ray_tpu start --address=<head_host:port> \
        --labels '{"provider_node_id": "'$(hostname)'"}'

— the ``provider_node_id`` label is how the reconciler pairs the cloud
instance with the ray node it became (``v2._reconcile_ray_nodes``).
"""

from __future__ import annotations

import time

from typing import Any, Optional


def _sanitize_label(v: str) -> str:
    from ray_tpu.autoscaler.gce import _sanitize

    return _sanitize(v)


def load_cluster_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    validate_cluster_config(cfg)
    return cfg


def validate_cluster_config(cfg: Any) -> None:
    if not isinstance(cfg, dict):
        raise ValueError("cluster config must be a mapping")
    for key in ("cluster_name", "provider", "node_types"):
        if key not in cfg:
            raise ValueError(f"cluster config missing required key {key!r}")
    unknown = set(cfg) - {
        "cluster_name", "provider", "head", "node_types",
        "idle_timeout_s", "update_interval_s",
    }
    if unknown:
        raise ValueError(f"unknown cluster config key(s) {sorted(unknown)}")
    prov = cfg["provider"]
    if not isinstance(prov, dict) or prov.get("type") not in (
        "gke_tpu", "gce_tpu", "fake"
    ):
        raise ValueError("provider.type must be 'gke_tpu', 'gce_tpu' or 'fake'")
    if prov["type"] == "gke_tpu":
        for key in ("project", "zone", "cluster"):
            if not prov.get(key):
                raise ValueError(f"provider.{key} is required for gke_tpu")
    if prov["type"] == "gce_tpu":
        for key in ("project", "zone"):
            if not prov.get(key):
                raise ValueError(f"provider.{key} is required for gce_tpu")
    if not isinstance(cfg["node_types"], dict) or not cfg["node_types"]:
        raise ValueError("node_types must be a non-empty mapping")
    for name, spec in cfg["node_types"].items():
        if not isinstance(spec, dict) or not isinstance(spec.get("resources"), dict):
            raise ValueError(f"node_types.{name}.resources is required")
        unknown_t = set(spec) - {
            "pool", "resources", "labels", "min_workers", "max_workers",
            # gce_tpu launch config (autoscaler/gce.py)
            "machine_type", "accelerator_type", "runtime_version",
            "source_image", "disk_size_gb", "network", "internal_ip_only",
            "startup_script",
        }
        if unknown_t:
            raise ValueError(f"unknown node_types.{name} key(s) {sorted(unknown_t)}")
        if spec.get("min_workers", 0) > spec.get("max_workers", 2**31):
            raise ValueError(f"node_types.{name}: min_workers > max_workers")


def build_provider(cfg: dict, cluster=None, client=None):
    """Provider from config. ``cluster`` backs the fake type; ``client``
    injects a transport into the GKE type (tests)."""
    prov = cfg["provider"]
    if prov["type"] == "fake":
        from ray_tpu.autoscaler.v2 import FakeAsyncProvider

        return FakeAsyncProvider(cluster=cluster, delay_polls=1)
    if prov["type"] == "gce_tpu":
        from ray_tpu.autoscaler.gce import GCEAsyncProvider

        kwargs = {}
        if client is not None:  # injected transport (tests)
            if isinstance(client, tuple):
                kwargs = {"gce_client": client[0], "tpu_client": client[1]}
            else:
                # single-client injection covers ONLY the compute path; a
                # TPU node type must fail loudly instead of falling back to
                # a REAL tpu.googleapis.com client under a fake
                class _RefuseTPU:
                    def __getattr__(self, name):
                        raise RuntimeError(
                            "TPU node types need a tpu client: inject "
                            "client=(gce_client, tpu_client)"
                        )

                kwargs = {"gce_client": client, "tpu_client": _RefuseTPU()}
        return GCEAsyncProvider(
            project=prov["project"],
            zone=prov["zone"],
            node_types=cfg["node_types"],
            cluster_name=cfg.get("cluster_name", ""),
            **kwargs,
        )
    from ray_tpu.autoscaler.gke import GKEClient, GKETPUAsyncProvider

    pools = {
        name: spec.get("pool", name) for name, spec in cfg["node_types"].items()
    }
    return GKETPUAsyncProvider(
        project=prov["project"],
        zone=prov["zone"],
        cluster_name=prov["cluster"],
        pools=pools,
        client=client
        or GKEClient(prov["project"], prov["zone"], prov["cluster"]),
    )


def run_cluster(
    cfg: dict,
    head,
    provider,
    ctx=None,
    max_ticks: Optional[int] = None,
    stop_check=None,
) -> dict:
    """The ``up`` reconcile loop: AutoscalerV2 against a live head.
    ``max_ticks`` bounds the loop (tests / one-shot reconcile); otherwise
    runs until ``stop_check()`` is truthy. Returns the last status counts."""
    from ray_tpu.autoscaler.v2 import AutoscalerV2

    scaler = AutoscalerV2(
        provider,
        cfg["node_types"],
        head=head,
        ctx=ctx,
        idle_timeout_s=float(cfg.get("idle_timeout_s", 60.0)),
    )
    interval = float(cfg.get("update_interval_s", 5.0))
    counts: dict = {}
    tick = 0
    errors = 0
    while True:
        try:
            counts = scaler.update()
            errors = 0
        except Exception as e:  # noqa: BLE001
            # a transient cloud 503 must not kill the control plane that
            # every worker and driver is connected to — log, back off, retry
            errors += 1
            print(f"[ray_tpu up] reconcile error ({errors}): {e}")
            time.sleep(min(interval * errors, 60.0))
        tick += 1
        if max_ticks is not None and tick >= max_ticks:
            return counts
        if stop_check is not None and stop_check():
            return counts
        time.sleep(interval)


def teardown_cluster(cfg: dict, client=None) -> list[str]:
    """The ``down`` path: delete every VM in every configured pool.
    Returns the terminated instance names (empty for the fake provider,
    whose virtual nodes die with the head process)."""
    prov = cfg["provider"]
    if prov["type"] == "fake":
        return []
    if prov["type"] == "gce_tpu":
        from ray_tpu.autoscaler.gce import GCEClient, TPUNodeClient

        if isinstance(client, tuple):
            gc, tc = client
        elif client is not None:
            # single injected client covers ONLY the compute sweep (the
            # tuple form injects both) — never dial a real TPU API from
            # under an injected fake
            gc, tc = client, None
        else:
            gc = GCEClient(prov["project"], prov["zone"])
            tc = TPUNodeClient(prov["project"], prov["zone"])
        # the label VALUE was sanitized at create time (GCE label charset);
        # the filter must compare the sanitized form or it matches nothing
        cluster = _sanitize_label(cfg.get("cluster_name", ""))
        gone = []
        # both API families: plain compute VMs AND tpu.googleapis.com
        # TPU-VM nodes (the expensive ones) carry the ray-cluster label
        for inst in gc.list_instances(f"labels.ray-cluster={cluster}"):
            gc.delete_instance(inst["name"])
            gone.append(inst["name"])
        for node in tc.list_nodes() if tc is not None else []:
            if node.get("labels", {}).get("ray-cluster") == cluster:
                name = node["name"].rsplit("/", 1)[-1]
                tc.delete_node(name)
                gone.append(name)
        return gone
    from ray_tpu.autoscaler.gke import GKEClient

    client = client or GKEClient(prov["project"], prov["zone"], prov["cluster"])
    gone: list[str] = []
    pools = {spec.get("pool", name) for name, spec in cfg["node_types"].items()}
    for pool in sorted(pools):  # dedup: node types may share a pool
        for vm in client.list_pool_instances(pool):
            client.delete_instance(pool, vm)
            gone.append(vm)
    return gone
