"""ray_tpu.autoscaler: demand-driven cluster scaling.

Reference: ``python/ray/autoscaler/`` — ``StandardAutoscaler.update``
(``_private/autoscaler.py:171,373``) driven by a monitor loop, launching
nodes through pluggable cloud ``NodeProvider``s, with the in-process
``FakeMultiNodeProvider`` (``_private/fake_multi_node/node_provider.py:237``)
powering e2e tests on one machine.
"""

from ray_tpu.autoscaler.autoscaler import Monitor, StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeNodeProvider,
    GKETPUNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.v2 import (  # noqa: F401
    AsyncNodeProvider,
    AutoscalerV2,
    FakeAsyncProvider,
    InstanceManager,
)
