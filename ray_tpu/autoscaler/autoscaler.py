"""StandardAutoscaler: pending-demand bin-packing → node launches; idle
nodes reaped after a timeout.

Reference: ``autoscaler/_private/autoscaler.py:171`` (StandardAutoscaler,
``update`` :373) + ``resource_demand_scheduler.py`` (fit pending resource
shapes against node types, launch the minimal set). Driven either by
explicit ``update()`` calls (tests) or the ``Monitor`` thread (the
reference's monitor.py process).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


def _fits(shape: dict, capacity: dict) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _sub(capacity: dict, shape: dict) -> dict:
    out = dict(capacity)
    for k, v in shape.items():
        out[k] = out.get(k, 0.0) - v
    return out


class StandardAutoscaler:
    """``node_types``: {name: {"resources": {...}, "max_workers": int,
    "min_workers": int}}. One provider node per launch."""

    def __init__(
        self,
        provider: NodeProvider,
        node_types: dict,
        idle_timeout_s: float = 30.0,
        launch_grace_s: float = 10.0,
        head=None,
        ctx=None,
    ):
        self.provider = provider
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.launch_grace_s = launch_grace_s
        self._head = head
        self._ctx = ctx
        self._launch_times: dict[str, float] = {}
        self._types: dict[str, str] = {}  # provider node id -> node type
        self._counts: dict[str, int] = {t: 0 for t in node_types}

    # -- demand feed -------------------------------------------------------

    def _demand(self) -> dict:
        if self._ctx is not None:
            return self._ctx.call("autoscaler_demand")
        if self._head is not None:
            return self._head.rpc_autoscaler_demand()
        from ray_tpu._private.runtime import get_ctx

        return get_ctx().call("autoscaler_demand")

    # -- one reconciliation pass ------------------------------------------

    def update(self) -> dict:
        """Returns {"launched": [...], "terminated": [...]} this pass."""
        feed = self._demand()
        launched, terminated = [], []

        # 1) ensure min_workers
        for t, cfg in self.node_types.items():
            while self._counts[t] < cfg.get("min_workers", 0):
                launched.append(self._launch(t))

        # 2) unmet demand: shapes that fit no live node's availability and
        # no in-grace freshly-launched capacity
        avail: list[tuple] = [  # (head node_id | None, capacity)
            (n["node_id"], dict(n["resources_available"]))
            for n in feed["nodes"]
            if n["alive"]
        ]
        now = time.monotonic()
        for pid, t0 in self._launch_times.items():
            if now - t0 < self.launch_grace_s and pid in self.provider.non_terminated_nodes():
                # capacity that is still materializing — count it
                avail.append((None, self.provider.node_resources(pid)))
        placed_on: set[str] = set()  # nodes step 3 must not reap this pass
        for shape in feed["pending_demand"]:
            if not shape:
                continue
            placed = False
            for i, (nid, cap) in enumerate(avail):
                if _fits(shape, cap):
                    avail[i] = (nid, _sub(cap, shape))
                    if nid is not None:
                        placed_on.add(nid)
                    placed = True
                    break
            if placed:
                continue
            # launch the smallest node type that can hold the shape
            for t, cfg in sorted(
                self.node_types.items(), key=lambda kv: sum(kv[1]["resources"].values())
            ):
                if _fits(shape, cfg["resources"]) and self._counts[t] < cfg.get(
                    "max_workers", 1
                ):
                    pid = self._launch(t)
                    launched.append(pid)
                    avail.append((None, _sub(self.provider.node_resources(pid), shape)))
                    break

        # 3) idle scale-down (never below min_workers; grace after launch)
        by_head_id = {}
        for pid in self.provider.non_terminated_nodes():
            hid = getattr(self.provider, "head_node_id_of", lambda p: None)(pid)
            if hid is not None:
                by_head_id[hid.hex()] = pid
        for n in feed["nodes"]:
            pid = by_head_id.get(n["node_id"])
            if pid is None or n["busy"] or n["idle_s"] < self.idle_timeout_s:
                continue
            if n["node_id"] in placed_on:
                continue  # step 2 just bin-packed pending demand onto it
            if now - self._launch_times.get(pid, 0.0) < self.launch_grace_s:
                continue
            node_type = self._types.get(pid)
            min_w = self.node_types.get(node_type, {}).get("min_workers", 0)
            if node_type and self._counts.get(node_type, 0) <= min_w:
                continue
            self.provider.terminate_node(pid)
            if node_type:
                self._counts[node_type] -= 1
            self._launch_times.pop(pid, None)
            terminated.append(pid)

        return {"launched": launched, "terminated": terminated}

    def _launch(self, node_type: str) -> str:
        cfg = self.node_types[node_type]
        pid = self.provider.create_node(
            node_type, cfg["resources"], labels={"autoscaled": "1"}
        )
        self._counts[node_type] += 1
        self._types[pid] = node_type
        self._launch_times[pid] = time.monotonic()
        return pid


class Monitor:
    """Background loop calling ``autoscaler.update()`` (reference:
    ``autoscaler/_private/monitor.py``)."""

    def __init__(self, autoscaler: StandardAutoscaler, interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        from ray_tpu._private.log_util import warn_throttled

        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception as e:
                warn_throttled("autoscaler monitor loop", e)
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
