"""Raw GCE / Cloud-TPU-VM provider: bare-metal TPU pods without GKE.

Reference: ``python/ray/autoscaler/_private/gcp/node_provider.py`` — the
reference's GCP provider creates instances directly (no Kubernetes); its
TPU support drives ``tpu.googleapis.com`` nodes next to plain compute VMs.
Same split here, dependency-free (urllib + the VM metadata server, both
shared with :mod:`ray_tpu.autoscaler.gke`):

* :class:`GCEClient` — ``compute.googleapis.com`` ``instances``
  insert/get/delete/list for CPU hosts;
* :class:`TPUNodeClient` — ``tpu.googleapis.com/v2`` TPU-VM nodes
  (``queuedResources``-free direct create; an ``accelerator_type`` in the
  node-type spec routes a create here);
* :class:`GCEAsyncProvider` — :class:`~ray_tpu.autoscaler.v2.AsyncNodeProvider`
  over both. Direct inserts let the provider choose the instance NAME and
  stamp labels up front, so pairing with ray nodes is exact (the GKE
  provider must instead diff managed-instance-group membership).

Transport is injectable (``http=`` callable) exactly like the GKE client,
so tests run against fakes and air-gapped CI never dials out.
"""

from __future__ import annotations

import re
from typing import Callable, Optional

from ray_tpu.autoscaler.gke import GKEClient
from ray_tpu.autoscaler.v2 import (
    ALLOCATED,
    ALLOCATION_FAILED,
    REQUESTED,
    AsyncNodeProvider,
    Instance,
)


def _is_404(e: Exception) -> bool:
    """Status-code-anchored not-found check: instance NAMES can contain
    '404' (ray-cpu-i-000404), so a bare substring match would classify a
    403/500 on such a name as not-found and swallow real failures."""
    return "failed: 404" in str(e)


def _sanitize(name: str) -> str:
    """GCE instance names: lowercase RFC-1035, <=63 chars."""
    out = re.sub(r"[^a-z0-9-]", "-", name.lower()).strip("-")
    return out[:63] or "node"


class GCEClient:
    """``compute.googleapis.com`` instances API (urllib; injectable)."""

    COMPUTE = "https://compute.googleapis.com/compute/v1"

    def __init__(
        self,
        project: str,
        zone: str,
        http: Optional[Callable[[str, str, Optional[dict]], dict]] = None,
        token_provider: Optional[Callable[[], str]] = None,
    ):
        self.project = project
        self.zone = zone
        # reuse the GKE client's urllib transport + token machinery: one
        # retry/auth/error-normalization implementation for all GCP APIs
        self._gke = GKEClient(
            project, zone, cluster="-", http=http, token_provider=token_provider
        )
        self._http = self._gke._http

    def _base(self) -> str:
        return f"{self.COMPUTE}/projects/{self.project}/zones/{self.zone}/instances"

    def insert_instance(self, name: str, config: dict, labels: dict) -> dict:
        body = {
            "name": name,
            "machineType": (
                f"zones/{self.zone}/machineTypes/"
                f"{config.get('machine_type', 'n2-standard-8')}"
            ),
            "disks": [
                {
                    "boot": True,
                    "autoDelete": True,
                    "initializeParams": {
                        "sourceImage": config.get(
                            "source_image",
                            "projects/debian-cloud/global/images/family/debian-12",
                        ),
                        "diskSizeGb": str(config.get("disk_size_gb", 100)),
                    },
                }
            ],
            "networkInterfaces": [
                {
                    "network": config.get("network", "global/networks/default"),
                    "accessConfigs": []
                    if config.get("internal_ip_only")
                    else [{"type": "ONE_TO_ONE_NAT"}],
                }
            ],
            "labels": {k: _sanitize(str(v)) for k, v in labels.items()},
            "metadata": {
                "items": [
                    {"key": "startup-script", "value": config["startup_script"]}
                ]
                if config.get("startup_script")
                else []
            },
        }
        return self._http("POST", self._base(), body)

    def get_instance(self, name: str) -> Optional[dict]:
        try:
            return self._http("GET", f"{self._base()}/{name}", None)
        except RuntimeError as e:
            if _is_404(e):
                return None
            raise

    def delete_instance(self, name: str) -> None:
        try:
            self._http("DELETE", f"{self._base()}/{name}", None)
        except RuntimeError as e:
            if not _is_404(e):
                raise

    def list_instances(self, label_filter: Optional[str] = None) -> list[dict]:
        from urllib.parse import quote

        out: list[dict] = []
        token = None
        while True:  # follow nextPageToken: a >1-page cluster must not
            params = []  # silently truncate (teardown would leak VMs)
            if label_filter:
                params.append(f"filter={quote(label_filter)}")
            if token:
                params.append(f"pageToken={quote(token)}")
            url = self._base() + ("?" + "&".join(params) if params else "")
            resp = self._http("GET", url, None)
            out.extend(resp.get("items", []))
            token = resp.get("nextPageToken")
            if not token:
                return out


class TPUNodeClient:
    """``tpu.googleapis.com/v2`` TPU-VM nodes (the bare-metal pod path)."""

    TPU = "https://tpu.googleapis.com/v2"

    def __init__(
        self,
        project: str,
        zone: str,
        http: Optional[Callable[[str, str, Optional[dict]], dict]] = None,
        token_provider: Optional[Callable[[], str]] = None,
    ):
        self.project = project
        self.zone = zone
        self._gke = GKEClient(
            project, zone, cluster="-", http=http, token_provider=token_provider
        )
        self._http = self._gke._http

    def _base(self) -> str:
        return f"{self.TPU}/projects/{self.project}/locations/{self.zone}/nodes"

    def create_node(self, name: str, config: dict, labels: dict) -> dict:
        body = {
            "acceleratorType": config["accelerator_type"],  # e.g. v5litepod-8
            "runtimeVersion": config.get("runtime_version", "tpu-ubuntu2204-base"),
            "labels": {k: _sanitize(str(v)) for k, v in labels.items()},
        }
        if config.get("startup_script"):
            body["metadata"] = {"startup-script": config["startup_script"]}
        return self._http("POST", f"{self._base()}?nodeId={name}", body)

    def get_node(self, name: str) -> Optional[dict]:
        try:
            return self._http("GET", f"{self._base()}/{name}", None)
        except RuntimeError as e:
            if _is_404(e):
                return None
            raise

    def delete_node(self, name: str) -> None:
        try:
            self._http("DELETE", f"{self._base()}/{name}", None)
        except RuntimeError as e:
            if not _is_404(e):
                raise

    def list_nodes(self) -> list[dict]:
        from urllib.parse import quote

        out: list[dict] = []
        token = None
        while True:
            url = self._base() + (f"?pageToken={quote(token)}" if token else "")
            resp = self._http("GET", url, None)
            out.extend(resp.get("nodes", []))
            token = resp.get("nextPageToken")
            if not token:
                return out


class GCEAsyncProvider(AsyncNodeProvider):
    """AsyncNodeProvider over direct GCE instances and/or TPU-VM nodes.

    ``node_types`` maps the autoscaler node-type name to its launch config;
    an ``accelerator_type`` key routes that type through the TPU API
    (bare-metal pods), anything else is a plain compute instance. The
    provider names instances after the autoscaler instance id and stamps
    ``provider_node_id`` both as a label and into the startup script's
    ``$RAY_TPU_NODE_ID`` substitution — the joining agent reports it via
    ``--labels`` and the reconciler pairs cloud and ray views exactly.
    """

    def __init__(
        self,
        project: str = "",
        zone: str = "",
        node_types: Optional[dict] = None,
        gce_client: Optional[GCEClient] = None,
        tpu_client: Optional[TPUNodeClient] = None,
        cluster_name: str = "",
    ):
        self.gce = gce_client or GCEClient(project, zone)
        self.tpu = tpu_client or TPUNodeClient(project, zone)
        self.node_types = dict(node_types or {})
        self.cluster_name = cluster_name
        self._kind: dict[str, str] = {}  # instance_id -> "tpu" | "gce"

    def _config_of(self, node_type: str) -> dict:
        return self.node_types.get(node_type, {})

    def request_create(self, instance: Instance, resources: dict, labels: dict) -> None:
        cfg = self._config_of(instance.node_type)
        name = _sanitize(f"ray-{instance.node_type}-{instance.instance_id}")
        stamped = dict(labels)
        stamped["provider_node_id"] = name
        if self.cluster_name:
            # teardown_cluster sweeps by this label — without it a
            # 'ray_tpu down' would find (and bill-stop) nothing
            stamped["ray-cluster"] = self.cluster_name
        cfg = dict(cfg)
        if cfg.get("startup_script"):
            cfg["startup_script"] = cfg["startup_script"].replace(
                "$RAY_TPU_NODE_ID", name
            )
        if cfg.get("accelerator_type"):
            self._kind[instance.instance_id] = "tpu"
            self.tpu.create_node(name, cfg, stamped)
        else:
            self._kind[instance.instance_id] = "gce"
            self.gce.insert_instance(name, cfg, stamped)
        instance.provider_id = name

    def poll(self, instance: Instance) -> str:
        kind = self._kind.get(instance.instance_id)
        if kind is None or not instance.provider_id:
            return ALLOCATION_FAILED
        try:
            if kind == "tpu":
                node = self.tpu.get_node(instance.provider_id)
                status = (node or {}).get("state", "")
                ready, failed = ("READY",), ("PREEMPTED", "TERMINATED")
            else:
                node = self.gce.get_instance(instance.provider_id)
                status = (node or {}).get("status", "")
                ready, failed = ("RUNNING",), ("TERMINATED", "STOPPED")
        except RuntimeError:
            return REQUESTED  # transient API error: keep polling
        if node is None:
            # not yet visible right after the insert — or actually gone;
            # the autoscaler's allocation timeout bounds the wait either way
            return REQUESTED
        if status in ready:
            return ALLOCATED
        if status in failed:
            return ALLOCATION_FAILED
        return REQUESTED

    def terminate(self, instance: Instance) -> None:
        if not instance.provider_id:
            return
        if self._kind.get(instance.instance_id) == "tpu" or (
            self._config_of(instance.node_type).get("accelerator_type")
        ):
            self.tpu.delete_node(instance.provider_id)
        else:
            self.gce.delete_instance(instance.provider_id)
