"""NodeProvider: the pluggable boundary between the autoscaler and whatever
actually creates machines.

Reference: ``python/ray/autoscaler/node_provider.py`` (create_node /
terminate_node / non_terminated_nodes / node_tags) and the in-process fake
(``autoscaler/_private/fake_multi_node/node_provider.py:237``). The fake
here registers virtual NodeStates against the live head via
``cluster_utils.Cluster.add_node`` — scheduling, worker spawn and task
execution on the "new machine" are all real; only the machine is virtual.

``GKETPUNodeProvider`` is the deployment-shaped stub: node types map to GKE
node pools of TPU slices (one provider "node" = one slice host group), and
create/terminate calls would go through the GKE API. It raises unless its
client is injected — keeping the control flow testable without egress.
"""

from __future__ import annotations

import uuid
from typing import Any, Optional


class NodeProvider:
    """Minimal provider surface the autoscaler drives."""

    def create_node(self, node_type: str, resources: dict, labels: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_resources(self, provider_node_id: str) -> dict:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """In-process provider over a ``cluster_utils.Cluster``."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._nodes: dict[str, Any] = {}   # provider id -> head NodeID
        self._meta: dict[str, dict] = {}

    def create_node(self, node_type: str, resources: dict, labels: dict) -> str:
        pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
        node_id = self.cluster.add_node(
            resources=dict(resources), labels={**labels, "node_type": node_type}
        )
        self._nodes[pid] = node_id
        self._meta[pid] = {"type": node_type, "resources": dict(resources)}
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        node_id = self._nodes.pop(provider_node_id, None)
        self._meta.pop(provider_node_id, None)
        if node_id is not None:
            self.cluster.remove_node(node_id)

    def non_terminated_nodes(self) -> list[str]:
        return list(self._nodes)

    def node_resources(self, provider_node_id: str) -> dict:
        return dict(self._meta[provider_node_id]["resources"])

    def head_node_id_of(self, provider_node_id: str):
        return self._nodes.get(provider_node_id)


class GKETPUNodeProvider(NodeProvider):
    """GKE TPU node-pool provider skeleton.

    Node types are TPU slice shapes (e.g. ``v5e-8``: one host of a v5e-8
    slice with resources ``{"TPU": 8, "CPU": 44, "tpu-v5e-8-head": 1}``).
    ``create_node`` scales the matching GKE node pool up by one;
    ``terminate_node`` deletes the VM. The GKE REST client must be injected
    (``client=``) — this image has no egress, so the default raises with the
    exact calls a deployment needs.
    """

    def __init__(
        self,
        project: str = "",
        zone: str = "",
        cluster_name: str = "",
        client: Optional[Any] = None,
    ):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.client = client
        self._nodes: dict[str, dict] = {}

    def _require_client(self, op: str):
        if self.client is None:
            raise RuntimeError(
                f"GKETPUNodeProvider.{op} needs a GKE client: inject one "
                f"implementing setNodePoolSize/deleteNode against "
                f"projects/{self.project}/zones/{self.zone}/clusters/{self.cluster_name}"
            )

    def create_node(self, node_type: str, resources: dict, labels: dict) -> str:
        self._require_client("create_node")
        pid = self.client.scale_up(node_pool=node_type, labels=labels)
        self._nodes[pid] = {"type": node_type, "resources": dict(resources)}
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        self._require_client("terminate_node")
        self.client.delete(provider_node_id)
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> list[str]:
        return list(self._nodes)

    def node_resources(self, provider_node_id: str) -> dict:
        return dict(self._nodes[provider_node_id]["resources"])
