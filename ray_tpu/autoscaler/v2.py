"""Autoscaler v2: explicit per-instance lifecycle driven by a reconciler.

Reference: ``python/ray/autoscaler/v2/`` — ``instance_manager/`` keeps one
state machine per INSTANCE (not per launch request) with validated
transitions and a status history, and a reconciler diffs desired state
against both the cloud provider and the ray cluster every tick. The v1
``StandardAutoscaler`` (autoscaler.py) launches fire-and-forget; this
module tracks each machine from QUEUED to TERMINATED, retries failed
allocations with backoff, and pairs cloud instances with the ray nodes
that eventually join.

Lite by design: in-memory instance table (the reference persists to the
GCS KV), cooperative AsyncNodeProvider interface (request/poll/terminate)
instead of cloud SDK threads. FakeAsyncProvider simulates slow allocation
and injected failures for tests; real providers implement the same three
methods.
"""

from __future__ import annotations

import itertools
import time
from typing import Optional

# -- instance FSM ------------------------------------------------------------

QUEUED = "QUEUED"                      # wanted; not yet requested from the cloud
REQUESTED = "REQUESTED"                # create call issued; waiting on the cloud
ALLOCATED = "ALLOCATED"                # machine exists; ray not up yet
RAY_RUNNING = "RAY_RUNNING"            # its ray node registered with the head
TERMINATING = "TERMINATING"            # terminate call issued
TERMINATED = "TERMINATED"              # gone (terminal)
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # cloud refused; retried with backoff

#: validated edges (reference: InstanceUtil.get_valid_transitions)
_TRANSITIONS: dict[str, set] = {
    QUEUED: {REQUESTED},
    REQUESTED: {ALLOCATED, ALLOCATION_FAILED},
    ALLOCATED: {RAY_RUNNING, TERMINATING},
    RAY_RUNNING: {TERMINATING},
    TERMINATING: {TERMINATED},
    ALLOCATION_FAILED: {QUEUED, TERMINATED},
    TERMINATED: set(),
}


class Instance:
    _ids = itertools.count(1)

    def __init__(self, node_type: str):
        self.instance_id = f"i-{next(Instance._ids):06d}"
        self.node_type = node_type
        self.status = QUEUED
        self.provider_id: Optional[str] = None
        self.ray_node_id: Optional[str] = None
        self.retries = 0
        self.next_retry_at = 0.0
        self.idle_since: Optional[float] = None
        self.status_history: list[tuple[str, float]] = [(QUEUED, time.time())]

    def set_status(self, status: str) -> None:
        if status not in _TRANSITIONS[self.status]:
            raise ValueError(
                f"invalid transition {self.status} -> {status} for {self.instance_id}"
            )
        self.status = status
        self.status_history.append((status, time.time()))


class InstanceManager:
    """The instance table + validated transitions (reference:
    instance_manager/instance_manager.py over instance_storage)."""

    def __init__(self):
        self.instances: dict[str, Instance] = {}

    def add(self, node_type: str) -> Instance:
        inst = Instance(node_type)
        self.instances[inst.instance_id] = inst
        return inst

    def with_status(self, *statuses: str) -> list[Instance]:
        return [i for i in self.instances.values() if i.status in statuses]

    def active(self) -> list[Instance]:
        return self.with_status(QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, ALLOCATION_FAILED)


class AsyncNodeProvider:
    """Cooperative cloud interface: requests return immediately; progress
    is observed by polling (reference: v2 node provider abstraction)."""

    def request_create(self, instance: Instance, resources: dict, labels: dict) -> None:
        """``labels`` are the node type's labels: the provider must stamp
        them on the launched node (plus ``instance_id``) or label-gated
        demand would never match the machine bought for it."""
        raise NotImplementedError

    def poll(self, instance: Instance) -> str:
        """Return the PROVIDER's view: REQUESTED (still pending), ALLOCATED,
        or ALLOCATION_FAILED."""
        raise NotImplementedError

    def terminate(self, instance: Instance) -> None:
        raise NotImplementedError


class AutoscalerV2:
    """Reconciler: demand + min/max workers → desired instances; every
    ``update()`` advances each instance one legal step (reference:
    v2 Reconciler.sync in autoscaler/v2/instance_manager/reconciler.py)."""

    def __init__(
        self,
        provider: AsyncNodeProvider,
        node_types: dict,
        head=None,
        ctx=None,
        idle_timeout_s: float = 30.0,
        max_allocation_retries: int = 3,
        retry_backoff_s: float = 2.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.im = InstanceManager()
        self._head = head
        self._ctx = ctx
        self.idle_timeout_s = idle_timeout_s
        self.max_allocation_retries = max_allocation_retries
        self.retry_backoff_s = retry_backoff_s

    # -- cluster feeds -----------------------------------------------------

    def _demand(self) -> dict:
        if self._ctx is not None:
            return self._ctx.call("autoscaler_demand")
        if self._head is not None:
            return self._head.rpc_autoscaler_demand()
        return {"pending_demand": [], "nodes": []}

    # -- reconciliation ----------------------------------------------------

    def update(self) -> dict:
        now = time.time()
        feed = self._demand()
        self._reconcile_ray_nodes(feed)
        self._scale_up(feed)
        self._drive_lifecycle(now)
        self._scale_down(feed, now)
        counts: dict[str, int] = {}
        for i in self.im.instances.values():
            counts[i.status] = counts.get(i.status, 0) + 1
        return counts

    def _capacity_of(self, node_type: str) -> dict:
        return dict(self.node_types[node_type].get("resources", {}))

    def _scale_up(self, feed: dict) -> None:
        """Bin-pack unplaceable demand + honor min_workers (reference:
        resource_demand_scheduler fitting pending shapes)."""
        active_by_type: dict[str, int] = {}
        for i in self.im.active():
            active_by_type[i.node_type] = active_by_type.get(i.node_type, 0) + 1
        # min workers first
        for t, spec in self.node_types.items():
            for _ in range(spec.get("min_workers", 0) - active_by_type.get(t, 0)):
                self.im.add(t)
                active_by_type[t] = active_by_type.get(t, 0) + 1
        # then demand: each unplaceable shape gets the first type that fits,
        # packing multiple shapes onto one pending instance's capacity —
        # but a hard-labeled shape only onto a type whose labels satisfy it
        pending_caps: list[tuple[dict, dict]] = [
            (self._capacity_of(i.node_type), self.node_types[i.node_type].get("labels", {}))
            for i in self.im.with_status(QUEUED, REQUESTED, ALLOCATED)
        ]
        label_reqs = feed.get("pending_demand_labels") or []
        for idx, shape in enumerate(feed.get("pending_demand", [])):
            hard_labels = label_reqs[idx] if idx < len(label_reqs) else {}
            shape = {k: v for k, v in shape.items() if v > 0}
            if not shape:
                continue
            if hard_labels and not any(
                all(spec.get("labels", {}).get(k) == v for k, v in hard_labels.items())
                for spec in self.node_types.values()
            ):
                continue  # no node type can ever satisfy these labels:
                # launching would ratchet useless instances to max_workers
            placed = False
            for cap, cap_labels in pending_caps:
                if any(cap_labels.get(k) != v for k, v in hard_labels.items()):
                    continue
                if all(cap.get(k, 0.0) >= v for k, v in shape.items()):
                    for k, v in shape.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            for t, spec in self.node_types.items():
                cap = self._capacity_of(t)
                if not all(cap.get(k, 0.0) >= v for k, v in shape.items()):
                    continue
                if active_by_type.get(t, 0) >= spec.get("max_workers", 2**31):
                    continue  # this type is full; a later type may still fit
                type_labels = spec.get("labels", {})
                if any(type_labels.get(k) != v for k, v in hard_labels.items()):
                    continue  # type can never satisfy the task's hard labels
                self.im.add(t)
                active_by_type[t] = active_by_type.get(t, 0) + 1
                for k, v in shape.items():
                    cap[k] -= v
                pending_caps.append((cap, type_labels))
                break

    def _drive_lifecycle(self, now: float) -> None:
        for inst in list(self.im.instances.values()):
            if inst.status == QUEUED:
                inst.set_status(REQUESTED)
                spec = self.node_types[inst.node_type]
                self.provider.request_create(
                    inst, self._capacity_of(inst.node_type), dict(spec.get("labels", {}))
                )
            elif inst.status == REQUESTED:
                seen = self.provider.poll(inst)
                if seen == ALLOCATED:
                    inst.set_status(ALLOCATED)
                elif seen == ALLOCATION_FAILED:
                    inst.set_status(ALLOCATION_FAILED)
                    inst.retries += 1
                    inst.next_retry_at = now + self.retry_backoff_s * inst.retries
            elif inst.status == ALLOCATION_FAILED:
                if inst.retries > self.max_allocation_retries:
                    inst.set_status(TERMINATED)
                elif now >= inst.next_retry_at:
                    inst.set_status(QUEUED)  # re-request next tick
            elif inst.status == TERMINATING:
                self.provider.terminate(inst)
                inst.set_status(TERMINATED)

    def _reconcile_ray_nodes(self, feed: dict) -> None:
        """Pair ALLOCATED instances with the ray nodes that joined, keyed by
        the provider's instance label on the node (reference: the
        reconciler's cloud-instance <-> ray-node matching)."""
        nodes = feed.get("nodes", [])
        by_label = {
            n.get("labels", {}).get("instance_id"): n for n in nodes if n.get("labels")
        }
        # cloud pools (GKE) can't stamp the autoscaler's instance_id on a VM
        # ahead of a resize — those nodes join labeled with their VM name
        # instead (the startup-script contract in autoscaler/gke.py)
        by_provider = {
            n.get("labels", {}).get("provider_node_id"): n
            for n in nodes
            if n.get("labels", {}).get("provider_node_id")
        }
        for inst in self.im.with_status(ALLOCATED):
            node = by_label.get(inst.instance_id) or by_provider.get(inst.provider_id)
            if node is not None:
                inst.ray_node_id = node.get("node_id")
                inst.set_status(RAY_RUNNING)

    def _scale_down(self, feed: dict, now: float) -> None:
        """Idle RAY_RUNNING instances beyond min_workers terminate after
        the idle timeout."""
        nodes = {n.get("node_id"): n for n in feed.get("nodes", [])}
        running_by_type: dict[str, list[Instance]] = {}
        for inst in self.im.with_status(RAY_RUNNING):
            running_by_type.setdefault(inst.node_type, []).append(inst)
            node = nodes.get(inst.ray_node_id)
            idle = bool(node) and not node.get("busy", False)
            if idle:
                if inst.idle_since is None:
                    inst.idle_since = now
            else:
                inst.idle_since = None
        for t, insts in running_by_type.items():
            floor = self.node_types[t].get("min_workers", 0)
            killable = sorted(
                (i for i in insts if i.idle_since is not None
                 and now - i.idle_since >= self.idle_timeout_s),
                key=lambda i: i.idle_since,
            )
            for inst in killable[: max(len(insts) - floor, 0)]:
                inst.set_status(TERMINATING)


class FakeAsyncProvider(AsyncNodeProvider):
    """Simulated cloud: allocation completes after ``delay_polls`` polls;
    ``fail_first`` injected failures before allocations succeed. On
    allocation the instance's ray node 'joins' the supplied cluster with an
    instance_id label, closing the reconcile loop like a real node would."""

    def __init__(self, cluster=None, delay_polls: int = 1, fail_first: int = 0):
        self.cluster = cluster
        self.delay_polls = delay_polls
        self.fail_first = fail_first
        self._polls: dict[str, int] = {}
        self._resources_by_id: dict[str, dict] = {}
        self._labels_by_id: dict[str, dict] = {}
        self.created: list[str] = []
        self.terminated: list[str] = []

    def request_create(self, instance: Instance, resources: dict, labels: dict) -> None:
        self._polls[instance.instance_id] = 0
        instance.provider_id = f"cloud-{instance.instance_id}"
        self._resources_by_id[instance.instance_id] = dict(resources)
        self._labels_by_id[instance.instance_id] = dict(labels)

    def poll(self, instance: Instance) -> str:
        self._polls[instance.instance_id] += 1
        if self._polls[instance.instance_id] < self.delay_polls:
            return REQUESTED
        if self.fail_first > 0:
            self.fail_first -= 1
            return ALLOCATION_FAILED
        self.created.append(instance.provider_id)
        if self.cluster is not None:
            node_id = self.cluster.add_node(
                resources=dict(self._resources_by_id[instance.instance_id]),
                labels={**self._labels_by_id[instance.instance_id],
                        "instance_id": instance.instance_id},
            )
            instance.ray_node_id = node_id.hex()
        return ALLOCATED

    def terminate(self, instance: Instance) -> None:
        self.terminated.append(instance.provider_id)
        if self.cluster is not None and instance.ray_node_id:
            from ray_tpu._private.ids import NodeID

            try:
                self.cluster.remove_node(NodeID(bytes.fromhex(instance.ray_node_id)))
            except Exception:
                pass
