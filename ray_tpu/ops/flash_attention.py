"""Pallas TPU flash attention (causal) with a full custom-VJP backward.

The blockwise online-softmax formulation (Flash Attention 2) — no (seq, seq)
score matrix ever reaches HBM, so memory is O(seq) and the MXU stays fed from
VMEM. Forward saves only out + logsumexp per row; backward recomputes scores
blockwise with two kernels (dQ, then dK/dV). All accumulation fp32, inputs
bf16/fp32.

TPU tiling notes: the logsumexp rows live as ``(bh, 8, seq)`` — value
broadcast over 8 sublanes so the (sublane, lane) block shape ``(8, block_q)``
satisfies Mosaic's (8, 128) fp32 tile constraint; backward consumes the
single meaningful sublane as ``(bh, 1, seq)`` full-dim blocks. Sequence
lengths must tile by 128 on the TPU path (the public entry falls back to the
XLA implementation otherwise).

This is the hot op behind ``ray_tpu.ops.attention.causal_attention`` — the
reference has no attention kernel of its own (user torch code runs inside
``train_loop_per_worker``); SURVEY.md §5.7 makes long-context attention a
first-class mandate for the TPU build. On non-TPU backends the same kernels
run under ``interpret=True`` so CI (virtual CPU mesh) exercises identical
code paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k):
    """One (bh, q-block) cell: online softmax over causal kv blocks."""
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale  # (BQ, D)
    q_start = qi * block_q

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    # only kv blocks at-or-before the diagonal contribute
    num_kv = (q_start + block_q + block_k - 1) // block_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(cols <= rows, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log(l)  # (BQ,)
    lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, block_q))


def _flash_fwd(q, k, v, *, block_q, block_k):
    bh, seq, d = q.shape
    scale = 1.0 / (d**0.5)
    grid = (bh, seq // block_q)
    out, lse8 = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, seq), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse8[:, :1, :]  # (bh, 1, seq)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_k):
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]      # (BQ,)
    delta = delta_ref[0, 0]  # (BQ,)
    q_start = qi * block_q
    num_kv = (q_start + block_q + block_k - 1) // block_k

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        p = jnp.where(cols <= rows, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, num_kv, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block_q, seq_len
):
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    k_start = ki * block_k
    num_q = seq_len // block_q
    first_q = k_start // block_q  # earliest q block the diagonal touches

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = scale * jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        rows = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        p = jnp.where(cols <= rows, jnp.exp(s - lse_blk[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, *, block_q, block_k):
    bh, seq, d = q.shape
    scale = 1.0 / (d**0.5)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # (bh, seq)
    delta = delta[:, None, :]  # (bh, 1, seq) — full-dim minor blocks tile fine

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_k=block_k),
        grid=(bh, seq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, block_q=block_q, seq_len=seq),
        grid=(bh, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), v.dtype),
        ],
        interpret=_interpret(),
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


def _pick_blocks(seq: int, block_q: int, block_k: int) -> tuple[int, int]:
    bq = min(block_q, seq)
    bk = min(block_k, seq)
    while seq % bq:
        bq //= 2
    while seq % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, block_q=block_q, block_k=block_k)
    return out


def _flash_core_fwd(q, k, v, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, block_q=block_q, block_k=block_k)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(block_q, block_k, res, do):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, do, block_q=block_q, block_k=block_k)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, block_q: int = 256, block_k: int = 512
) -> jax.Array:
    """Causal flash attention. q,k,v: (batch, heads, seq, head_dim).

    O(seq) memory; differentiable (custom VJP with blockwise-recompute
    backward). On TPU, seq must tile by 128 (Mosaic lane constraint) — falls
    back to the XLA path otherwise; interpret mode (CPU CI) accepts any
    power-of-two-friendly blocking.
    """
    b, h, s, d = q.shape
    bq, bk = _pick_blocks(s, block_q, block_k)
    if not _interpret() and (bq % 128 or bk % 128):
        from ray_tpu.ops.attention import _xla_attention

        return _xla_attention(q, k, v)
    merge = lambda t: t.reshape(b * h, s, d)  # noqa: E731
    out = _flash_core(merge(q), merge(k), merge(v), bq, bk)
    return out.reshape(b, h, s, d)


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, mesh) -> jax.Array:
    """Flash attention inside a dp/fsdp/tp-sharded pjit program.

    A bare ``pallas_call`` has no GSPMD partitioning rule, so calling
    ``flash_attention`` directly under a multi-device pjit makes XLA
    all-gather q/k/v and replicate the kernel on every chip. This wrapper
    shard_maps it — batch over (dp, fsdp), heads over tp, seq/head_dim local
    — so each chip runs the kernel on exactly its shard (attention has no
    cross-batch/cross-head communication). Falls back to the caller's XLA
    path via ValueError when shapes don't divide the mesh.
    """
    from jax.sharding import PartitionSpec as P

    b, h, s, d = q.shape
    dp = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    tp = mesh.shape.get("tp", 1)
    if b % dp or h % tp:
        raise ValueError(f"batch {b} / heads {h} don't divide mesh axes dp*fsdp={dp}, tp={tp}")
    spec = P(("dp", "fsdp"), "tp", None, None)
    fn = jax.shard_map(
        flash_attention, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
