"""Pallas TPU flash attention (causal) with a full custom-VJP backward.

The blockwise online-softmax formulation (Flash Attention 2) — no (seq, seq)
score matrix ever reaches HBM and no kernel instance ever holds more than one
(block_q, d) + (block_k, d) working set in VMEM, so memory is O(seq) in HBM
and O(block) in VMEM at ANY sequence length. Forward saves only out +
logsumexp per row; backward recomputes scores blockwise with two kernels
(dQ, then dK/dV). All accumulation fp32, inputs bf16/fp32.

Grid layout: ``(bh, q_block, kv_block)`` with the KV dimension minor — TPU
grids execute the minor dimension sequentially, so VMEM scratch accumulators
(acc/m/l for forward, dq / dk+dv for backward) carry across KV (resp. Q)
steps of one output block and are flushed on the block's last step.
Causally-dead (q, kv) cells are skipped with ``pl.when``.

TPU tiling notes: per-row stats (logsumexp, delta) live as ``(bh, 8, seq)``
— value broadcast over 8 sublanes so the (sublane, lane) block shape
``(8, block_q)`` satisfies Mosaic's (8, 128) fp32 tile constraint. Sequence
lengths must tile by 128 on the TPU path (the public entry falls back to the
XLA implementation otherwise).

This is the hot op behind ``ray_tpu.ops.attention.causal_attention`` — the
reference has no attention kernel of its own (user torch code runs inside
``train_loop_per_worker``); SURVEY.md §5.7 makes long-context attention a
first-class mandate for the TPU build. On non-TPU backends the same kernels
run under ``interpret=True`` so CI (virtual CPU mesh) exercises identical
code paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _causal_mask(q_start, k_start, block_q, block_k):
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    return cols <= rows


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *, scale):
    """Grid (bh, qi, kj), kj minor/sequential. Scratch carries the online
    softmax state across kj steps of one q block."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    q_start = qi * block_q
    k_start = kj * block_k
    j_last = (q_start + block_q - 1) // block_k  # last causally-live kv block

    @pl.when(k_start <= q_start + block_q - 1)  # skip causally-dead cells
    def _():
        @pl.when(kj == 0)
        def _():
            m_sc[:] = jnp.full_like(m_sc, NEG_INF)
            l_sc[:] = jnp.zeros_like(l_sc)
            acc_sc[:] = jnp.zeros_like(acc_sc)

        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        s = jnp.where(_causal_mask(q_start, k_start, block_q, block_k), s, NEG_INF)
        m_prev = m_sc[0]
        l_prev = l_sc[0]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=1)
        acc_sc[:] = acc_sc[:] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_sc[:] = jnp.broadcast_to(m_new[None, :], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[None, :], l_sc.shape)

        @pl.when(kj == j_last)
        def _():
            l = jnp.maximum(l_sc[0], 1e-30)
            o_ref[0] = (acc_sc[:] / l[:, None]).astype(o_ref.dtype)
            lse = m_sc[0] + jnp.log(l)
            lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _flash_fwd(q, k, v, *, block_q, block_k):
    bh, seq, d = q.shape
    scale = 1.0 / (d**0.5)
    grid = (bh, seq // block_q, seq // block_k)
    out, lse8 = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, seq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, block_q), jnp.float32),   # running max (broadcast)
            pltpu.VMEM((8, block_q), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse8[:, :1, :]  # (bh, 1, seq)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc, *, scale):
    qi, kj = pl.program_id(1), pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    q_start, k_start = qi * block_q, kj * block_k
    j_last = (q_start + block_q - 1) // block_k

    @pl.when(k_start <= q_start + block_q - 1)
    def _():
        @pl.when(kj == 0)
        def _():
            dq_sc[:] = jnp.zeros_like(dq_sc)

        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        p = jnp.where(
            _causal_mask(q_start, k_start, block_q, block_k),
            jnp.exp(s - lse[:, None]),
            0.0,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

        @pl.when(kj == j_last)
        def _():
            dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc, *, scale
):
    """Grid (bh, kb, qi), qi minor/sequential; accumulates dk/dv for one kv
    block across its causally-live q blocks."""
    kb, qi = pl.program_id(1), pl.program_id(2)
    block_k, d = k_ref.shape[1], k_ref.shape[2]
    block_q = q_ref.shape[1]
    k_start, q_start = kb * block_k, qi * block_q
    i_first = k_start // block_q     # first q block the diagonal touches
    n_q = pl.num_programs(2)

    @pl.when(q_start + block_q - 1 >= k_start)
    def _():
        @pl.when(qi == i_first)
        def _():
            dk_sc[:] = jnp.zeros_like(dk_sc)
            dv_sc[:] = jnp.zeros_like(dv_sc)

        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK)
        p = jnp.where(
            _causal_mask(q_start, k_start, block_q, block_k),
            jnp.exp(s - lse[:, None]),
            0.0,
        )
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None]) * scale
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

        @pl.when(qi == n_q - 1)
        def _():
            dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, *, block_q, block_k):
    bh, seq, d = q.shape
    scale = 1.0 / (d**0.5)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # (bh, seq)
    delta = delta[:, None, :]  # (bh, 1, seq)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale),
        grid=(bh, seq // block_q, seq // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale),
        grid=(bh, seq // block_k, seq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, kk, i: (b, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, kk, i: (b, kk, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, kk, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, kk, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, kk, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, kk, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, kk, i: (b, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, kk, i: (b, kk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


def _pick_blocks(seq: int, block_q: int, block_k: int) -> tuple[int, int]:
    bq = min(block_q, seq)
    bk = min(block_k, seq)
    while seq % bq:
        bq //= 2
    while seq % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, block_q, block_k, block_q_bwd, block_k_bwd):
    out, _ = _flash_fwd(q, k, v, block_q=block_q, block_k=block_k)
    return out


def _flash_core_fwd(q, k, v, block_q, block_k, block_q_bwd, block_k_bwd):
    out, lse = _flash_fwd(q, k, v, block_q=block_q, block_k=block_k)
    # Name the kernel's own residuals so a jax.checkpoint policy
    # (save_only_these_names, models/gpt.py remat_policy="attn"/"big") can
    # keep exactly these and dead-code the whole forward kernel out of the
    # rematerialized backward — the single biggest recompute in a
    # full-remat transformer block.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_core_bwd(block_q, block_k, block_q_bwd, block_k_bwd, res, do):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, do, block_q=block_q_bwd, block_k=block_k_bwd)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _env_block(name: str, default: int) -> int:
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        # a typo'd sweep var must fail loudly, or every sweep point silently
        # benchmarks the identical default configuration
        raise ValueError(f"{name}={raw!r} is not an integer block size") from None


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    block_q: int | None = None,
    block_k: int | None = None,
    block_q_bwd: int | None = None,
    block_k_bwd: int | None = None,
) -> jax.Array:
    """Causal flash attention. q,k,v: (batch, heads, seq, head_dim).

    O(seq) HBM / O(block) VMEM; differentiable (custom VJP with
    blockwise-recompute backward). Forward and backward block shapes tune
    independently (the dQ/dKV kernels have different reuse patterns than the
    forward); defaults are overridable via RAY_TPU_FLASH_{BQ,BK,BQB,BKB} for
    sweeps. On TPU, seq must tile by 128 (Mosaic lane constraint) — falls
    back to the XLA path otherwise; interpret mode (CPU CI) accepts any
    power-of-two-friendly blocking.
    """
    b, h, s, d = q.shape
    # Default 1024×1024 measured fastest on v5e at (bh 256, s 1024, d 64):
    # fewer, fatter grid steps win — the kernel is latency-bound per step at
    # small head_dim, not VMEM-bound (sweep: 4.1 ms/layer at 256×512 →
    # 2.6 ms at 1024×1024; jax's own tuned kernel measures 2.2 at this
    # shape). _pick_blocks clamps to the actual sequence length.
    block_q = block_q if block_q is not None else _env_block("RAY_TPU_FLASH_BQ", 1024)
    block_k = block_k if block_k is not None else _env_block("RAY_TPU_FLASH_BK", 1024)
    block_q_bwd = (
        block_q_bwd if block_q_bwd is not None else _env_block("RAY_TPU_FLASH_BQB", block_q)
    )
    block_k_bwd = (
        block_k_bwd if block_k_bwd is not None else _env_block("RAY_TPU_FLASH_BKB", block_k)
    )
    bq, bk = _pick_blocks(s, block_q, block_k)
    bqb, bkb = _pick_blocks(s, block_q_bwd, block_k_bwd)
    # gate polarity matters to raylint RL022: `not _interpret() and ...`
    # only skips the pallas path ON TPU with bad tiling — off-TPU CI still
    # exercises the kernel interpreted, so no INTERPRET_ONLY entry is due
    # here (contrast ops/paged_attention.py, which routes AWAY off-TPU)
    if not _interpret() and (bq % 128 or bk % 128 or bqb % 128 or bkb % 128):
        from ray_tpu.ops.attention import _xla_attention

        return _xla_attention(q, k, v)
    merge = lambda t: t.reshape(b * h, s, d)  # noqa: E731
    out = _flash_core(merge(q), merge(k), merge(v), bq, bk, bqb, bkb)
    return out.reshape(b, h, s, d)


def flash_shardable(batch: int, heads: int, mesh) -> bool:
    """True when (batch, heads) divide the mesh's (dp*fsdp, tp) axes — the
    precondition for ``flash_attention_sharded``."""
    dp = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    tp = mesh.shape.get("tp", 1)
    return batch % dp == 0 and heads % tp == 0


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, mesh) -> jax.Array:
    """Flash attention inside a dp/fsdp/tp-sharded pjit program.

    A bare ``pallas_call`` has no GSPMD partitioning rule, so calling
    ``flash_attention`` directly under a multi-device pjit makes XLA
    all-gather q/k/v and replicate the kernel on every chip. This wrapper
    shard_maps it — batch over (dp, fsdp), heads over tp, seq/head_dim local
    — so each chip runs the kernel on exactly its shard (attention has no
    cross-batch/cross-head communication). Callers must check
    ``flash_shardable`` first.
    """
    from jax.sharding import PartitionSpec as P

    b, h, s, d = q.shape
    if not flash_shardable(b, h, mesh):
        raise ValueError(
            f"batch {b} / heads {h} don't divide mesh axes "
            f"dp*fsdp={mesh.shape.get('dp', 1) * mesh.shape.get('fsdp', 1)}, "
            f"tp={mesh.shape.get('tp', 1)}"
        )
    spec = P(("dp", "fsdp"), "tp", None, None)
    from ray_tpu._private.jax_compat import shard_map

    fn = shard_map(
        flash_attention, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
