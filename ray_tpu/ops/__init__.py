"""TPU compute ops: attention (XLA + Pallas flash), ring collectives.

Hot ops live here so models stay architecture-only. The reference has no
equivalent layer (its compute is torch inside user training loops); on TPU
these ops are where MXU utilization and HBM traffic are won.
"""

from ray_tpu.ops.attention import causal_attention  # noqa: F401
