"""Ring attention: causal attention with the sequence axis sharded over the
``sp`` mesh axis, KV blocks rotating around the ring via ``ppermute``.

Sequence/context parallelism is absent from the reference (SURVEY.md §5.7 —
"no ring attention, Ulysses, context-parallel, or blockwise attention
anywhere"); the TPU build makes it first-class: each device holds a
``seq/sp`` slice of Q/K/V, computes blockwise online-softmax partials of its
Q slice against the KV slice currently resident, then passes KV to its ring
neighbor over ICI. After ``sp`` hops every Q row has seen every allowed K —
O(seq/sp) memory per chip, compute overlapped with the ICI transfer by XLA's
latency-hiding scheduler.

The per-hop partial merge is the same online-softmax algebra as the flash
kernel (``ops/flash_attention.py``); fully-masked hops (KV chunk strictly in
the causal future) contribute zero weight. Differentiable end-to-end —
``ppermute`` transposes to the reverse rotation in the backward pass;
``jax.checkpoint`` on the hop body keeps backward memory at one hop's
activations.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _chunk_partials(q_scaled, k, v, q_off, k_off):
    """Blockwise softmax partials of one Q slice vs one KV chunk.

    q_scaled (B,H,Sq,D) fp32 already scaled; returns (m (B,H,Sq),
    l (B,H,Sq), acc (B,H,Sq,D)) with zero weight on causally-masked keys.
    """
    sq, sk = q_scaled.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, k.astype(jnp.float32))
    rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    allowed = cols <= rows
    s = jnp.where(allowed, s, NEG_INF)
    m = s.max(axis=-1)
    p = jnp.where(allowed, jnp.exp(s - m[..., None]), 0.0)
    l = p.sum(axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str = "sp") -> jax.Array:
    """Causal attention over ring-sharded sequences. MUST run inside a
    ``shard_map`` (or equivalent SPMD region) where ``axis_name`` is a mesh
    axis and q,k,v are the LOCAL (batch, heads, seq/sp, head_dim) slices,
    sharded contiguously in sequence order.
    """
    from ray_tpu._private.jax_compat import axis_size

    sp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = 1.0 / (d**0.5)
    q32 = q.astype(jnp.float32) * scale
    q_off = idx * s_local

    m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    acc = jnp.zeros((b, h, s_local, d), jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def hop(carry, kv, k_chunk_idx):
        m, l, acc = carry
        k_cur, v_cur = kv
        cm, cl, cacc = _chunk_partials(q32, k_cur, v_cur, q_off, k_chunk_idx * s_local)
        m_new = jnp.maximum(m, cm)
        corr = jnp.exp(m - m_new)
        ccorr = jnp.exp(cm - m_new)
        l = l * corr + cl * ccorr
        acc = acc * corr[..., None] + cacc * ccorr[..., None]
        return (m_new, l, acc)

    kv = (k, v)
    # static python loop: sp is a mesh constant, so this unrolls into sp
    # compute+ppermute stages XLA can pipeline.
    for r in range(sp):
        k_chunk_idx = (idx - r) % sp
        carry = hop((m, l, acc), kv, k_chunk_idx)
        m, l, acc = carry
        if r != sp - 1:
            kv = jax.tree_util.tree_map(
                lambda t: jax.lax.ppermute(t, axis_name, perm), kv
            )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, mesh) -> jax.Array:
    """shard_map wrapper: q,k,v global (batch, heads, seq, head_dim) arrays
    with batch over (dp,fsdp), heads over tp, seq over sp. Usable inside jit
    (e.g. from the GPT block under pjit)."""
    spec = P(("dp", "fsdp"), "tp", "sp", None)
    from ray_tpu._private.jax_compat import shard_map

    fn = shard_map(
        functools.partial(ring_attention, axis_name="sp"),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
