"""Blockwise (fused) softmax cross-entropy over a large vocabulary.

The naive loss materializes ``(tokens, vocab)`` fp32 logits **and** their
log-softmax — for the 406M GPT bench shape (16×1023 tokens × 50304 vocab)
that is ~6.6 GB of HBM, the single largest consumer in the training step —
and runs the lm-head matmul in fp32 (≤⅛ MXU throughput). This op computes
the exact same loss without ever materializing more than one vocab chunk of
logits, with matmuls in the activation dtype (bf16) accumulating in fp32:

* forward: one online-softmax pass over vocab chunks (running max / sum of
  exponentials / target-logit gather), keeping only ``(N,)`` statistics;
* backward (custom VJP): recompute each chunk's logits, form
  ``softmax − one-hot`` scaled by the cotangent, and accumulate ``dx`` and
  ``dW`` chunk by chunk (the ``(d, V)`` weight gradient is the only full-
  vocab tensor, and it must exist anyway).

Residuals are just ``x`` and the ``(N,)`` logsumexp — the flash-attention
trick applied to the classifier head (same decomposition as the reference's
fused/chunked losses, e.g. megatron's vocab-parallel cross entropy; built
here as a jittable lax.scan so XLA tiles the chunk matmuls onto the MXU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pick_chunks(vocab: int, n_chunks: int | None) -> int:
    if n_chunks is None:
        import os

        raw = os.environ.get("RAY_TPU_CE_CHUNKS")  # sweep knob
        if raw:
            n_chunks = int(raw)
    if n_chunks is not None:
        if vocab % n_chunks:
            raise ValueError(f"n_chunks={n_chunks} must divide vocab={vocab}")
        return n_chunks
    # Prefer the FINEST chunking whose chunks stay lane-ALIGNED (% 128) and
    # >= 4096 columns: a misaligned chunk width (e.g. 50304/32 = 1572) pads
    # on the MXU every step. Fall back to power-of-two chunking >= 1024
    # when the vocab's 128-quotient has no usable divisors.
    q, rem = divmod(vocab, 128)
    if rem == 0:
        best = 1
        for k in range(1, 65):
            if q % k == 0 and (vocab // k) % 128 == 0 and vocab // k >= 4096:
                best = k
        if best > 1 or vocab <= 8192:
            return best
        # q has no small divisors (prime-ish): one aligned chunk beats many
        # padded ones only for small vocabs; otherwise chunk misaligned
    k = 1
    while k < 64 and vocab % (k * 2) == 0 and vocab // (k * 2) >= 1024:
        k *= 2
    return k


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_softmax_cross_entropy(x, w, targets, n_chunks=None):
    """Per-token cross-entropy ``logsumexp(x@w) - (x@w)[target]``.

    Args:
      x: ``(N, d)`` activations (bf16 recommended; matmuls run in ``x.dtype``
        with fp32 accumulation).
      w: ``(d, V)`` classifier weights (cast to ``x.dtype`` for the matmul).
      targets: ``(N,)`` int32 class ids.
      n_chunks: vocab chunk count (must divide V); None = auto.

    Returns:
      ``(N,)`` fp32 per-token losses. ``jnp.mean`` of this equals the naive
      ``-log_softmax(x @ w)[target]`` mean up to input-dtype rounding.
    """
    losses, _ = _forward(x, w, targets, _pick_chunks(w.shape[1], n_chunks))
    return losses


def _chunk_logits(x, w, k, chunk):
    """fp32 logits for vocab chunk k, computed in x.dtype on the MXU."""
    wc = jax.lax.dynamic_slice_in_dim(w, k * chunk, chunk, axis=1)
    return jax.lax.dot_general(
        x,
        wc.astype(x.dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _forward(x, w, targets, n_chunks):
    n, d = x.shape
    v = w.shape[1]
    chunk = v // n_chunks

    def body(carry, k):
        m, s, tl = carry
        logits = _chunk_logits(x, w, k, chunk)            # (N, chunk) fp32
        cmax = logits.max(axis=-1)
        m_new = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        # gather this chunk's target logits (0 for out-of-chunk targets)
        local = targets - k * chunk
        in_chunk = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1
        )[:, 0]
        tl = tl + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, tl), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, s, tl), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    return lse - tl, lse


def _fwd(x, w, targets, n_chunks):
    losses, lse = _forward(x, w, targets, _pick_chunks(w.shape[1], n_chunks))
    return losses, (x, w, targets, lse)


def _bwd(n_chunks, res, g):
    x, w, targets, lse = res
    n, d = x.shape
    v = w.shape[1]
    k_chunks = _pick_chunks(v, n_chunks)
    chunk = v // k_chunks

    def body(carry, k):
        dx, dw = carry
        logits = _chunk_logits(x, w, k, chunk)            # recompute (N, chunk)
        p = jnp.exp(logits - lse[:, None])                # softmax chunk
        local = targets - k * chunk
        in_chunk = (local >= 0) & (local < chunk)
        onehot = (
            local[:, None] == jnp.arange(chunk, dtype=targets.dtype)[None, :]
        ) & in_chunk[:, None]
        dlogits = ((p - onehot.astype(jnp.float32)) * g[:, None]).astype(x.dtype)
        wc = jax.lax.dynamic_slice_in_dim(w, k * chunk, chunk, axis=1)
        dx = dx + jax.lax.dot_general(
            dlogits,
            wc.astype(x.dtype),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dwc = jax.lax.dot_general(
            x,
            dlogits,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dw = jax.lax.dynamic_update_slice_in_dim(dw, dwc, k * chunk, axis=1)
        return (dx, dw), None

    init = (jnp.zeros((n, d), jnp.float32), jnp.zeros((d, v), jnp.float32))
    (dx, dw), _ = jax.lax.scan(body, init, jnp.arange(k_chunks))
    return dx.astype(x.dtype), dw.astype(w.dtype), None


fused_softmax_cross_entropy.defvjp(_fwd, _bwd)
