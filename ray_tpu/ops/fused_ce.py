"""Blockwise (fused) softmax cross-entropy over a large vocabulary.

The naive loss materializes ``(tokens, vocab)`` fp32 logits **and** their
log-softmax — for the 406M GPT bench shape (16×1023 tokens × 50304 vocab)
that is ~6.6 GB of HBM, the single largest consumer in the training step —
and runs the lm-head matmul in fp32 (≤⅛ MXU throughput). This op computes
the exact same loss without ever materializing more than one vocab chunk of
logits, with matmuls in the activation dtype (bf16) accumulating in fp32:

* forward: one online-softmax pass over vocab chunks (running max / sum of
  exponentials / target-logit gather), keeping only ``(N,)`` statistics;
* backward (custom VJP): recompute each chunk's logits, form
  ``softmax − one-hot`` scaled by the cotangent, and accumulate ``dx`` and
  ``dW`` chunk by chunk (the ``(d, V)`` weight gradient is the only full-
  vocab tensor, and it must exist anyway).

Residuals are just ``x`` and the ``(N,)`` logsumexp — the flash-attention
trick applied to the classifier head (same decomposition as the reference's
fused/chunked losses, e.g. megatron's vocab-parallel cross entropy; built
here as a jittable lax.scan so XLA tiles the chunk matmuls onto the MXU).

One shared implementation serves both public entry points:
``fused_softmax_cross_entropy`` (GPT-2-family heads, no bias) and
``fused_softmax_cross_entropy_bias`` (GPT-J's biased untied head) — the
bias threads through as an optional static presence, so numeric fixes land
once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pick_chunks(vocab: int, n_chunks: int | None) -> int:
    if n_chunks is None:
        import os

        raw = os.environ.get("RAY_TPU_CE_CHUNKS")  # sweep knob
        if raw:
            n_chunks = int(raw)
    if n_chunks is not None:
        if vocab % n_chunks:
            raise ValueError(f"n_chunks={n_chunks} must divide vocab={vocab}")
        return n_chunks
    # Prefer the FINEST chunking whose chunks stay lane-ALIGNED (% 128) and
    # >= 4096 columns: a misaligned chunk width (e.g. 50304/32 = 1572) pads
    # on the MXU every step. Fall back to power-of-two chunking >= 1024
    # when the vocab's 128-quotient has no usable divisors.
    q, rem = divmod(vocab, 128)
    if rem == 0:
        best = 1
        for k in range(1, 65):
            if q % k == 0 and (vocab // k) % 128 == 0 and vocab // k >= 4096:
                best = k
        if best > 1 or vocab <= 8192:
            return best
        # q has no small divisors (prime-ish): one aligned chunk beats many
        # padded ones only for small vocabs; otherwise chunk misaligned
    k = 1
    while k < 64 and vocab % (k * 2) == 0 and vocab // (k * 2) >= 1024:
        k *= 2
    return k


def _save_logits() -> bool:
    """Opt-in residual mode (RAY_TPU_CE_SAVE_LOGITS=1): keep the bf16
    logits from the forward and skip the backward's recompute matmul — one
    lm-head matmul fewer per step for one (N, V) activation-dtype tensor of
    HBM (~2.7 GB at the 406M bench shape). Worth it only when the batch
    leaves that much headroom; the default streams with O(N) residuals."""
    import os

    return os.environ.get("RAY_TPU_CE_SAVE_LOGITS") == "1"


def _chunk_logits(x, w, k, chunk, b32):
    """fp32 logits for vocab chunk k (plus optional bias slice), computed
    in x.dtype on the MXU."""
    wc = jax.lax.dynamic_slice_in_dim(w, k * chunk, chunk, axis=1)
    logits = jax.lax.dot_general(
        x,
        wc.astype(x.dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if b32 is not None:
        logits = logits + jax.lax.dynamic_slice_in_dim(b32, k * chunk, chunk)[None, :]
    return logits


def _ce_forward(x, w, b, targets, n_chunks):
    """Shared streaming forward. ``b`` may be None. Returns (losses, lse)."""
    n, d = x.shape
    v = w.shape[1]
    chunk = v // n_chunks
    b32 = None if b is None else b.astype(jnp.float32)

    def body(carry, k):
        m, s, tl = carry
        logits = _chunk_logits(x, w, k, chunk, b32)       # (N, chunk) fp32
        cmax = logits.max(axis=-1)
        m_new = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        # gather this chunk's target logits (0 for out-of-chunk targets)
        local = targets - k * chunk
        in_chunk = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[:, None], axis=-1
        )[:, 0]
        tl = tl + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, tl), None

    init = (
        jnp.full((n,), -jnp.inf, jnp.float32),
        jnp.zeros((n,), jnp.float32),
        jnp.zeros((n,), jnp.float32),
    )
    (m, s, tl), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    return lse - tl, lse


def _ce_fwd(x, w, b, targets, n_chunks):
    """Shared custom-VJP forward. Residual logits16 is non-None only in
    save-logits mode (one (N, V) bf16 tensor buys the backward's matmul)."""
    if _save_logits():
        logits16 = jax.lax.dot_general(
            x, w.astype(x.dtype), (((1,), (0,)), ((), ()))
        )  # (N, V) in activation dtype
        logits = logits16.astype(jnp.float32)
        if b is not None:
            logits = logits + b.astype(jnp.float32)[None, :]
        m = logits.max(axis=-1)
        lse = m + jnp.log(jnp.exp(logits - m[:, None]).sum(axis=-1))
        tl = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
        return lse - tl, (x, w, b, targets, lse, logits16)
    losses, lse = _ce_forward(x, w, b, targets, _pick_chunks(w.shape[1], n_chunks))
    return losses, (x, w, b, targets, lse, None)


def _ce_bwd(n_chunks, res, g):
    """Shared backward: (dx, dw, db-or-None)."""
    x, w, b, targets, lse, logits16 = res
    n, d = x.shape
    v = w.shape[1]
    if logits16 is not None:
        logits = logits16.astype(jnp.float32)
        if b is not None:
            logits = logits + b.astype(jnp.float32)[None, :]
        p = jnp.exp(logits - lse[:, None])
        onehot = jax.nn.one_hot(targets, v, dtype=jnp.float32)
        dl32 = (p - onehot) * g[:, None]
        dlogits = dl32.astype(x.dtype)
        dx = jax.lax.dot_general(
            dlogits, w.astype(x.dtype), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dw = jax.lax.dot_general(
            x, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        db = None if b is None else dl32.sum(axis=0).astype(b.dtype)
        return dx.astype(x.dtype), dw.astype(w.dtype), db
    k_chunks = _pick_chunks(v, n_chunks)
    chunk = v // k_chunks
    b32 = None if b is None else b.astype(jnp.float32)
    with_bias = b is not None

    def body(carry, k):
        dx, dw, db = carry
        logits = _chunk_logits(x, w, k, chunk, b32)       # recompute (N, chunk)
        p = jnp.exp(logits - lse[:, None])                # softmax chunk
        local = targets - k * chunk
        in_chunk = (local >= 0) & (local < chunk)
        onehot = (
            local[:, None] == jnp.arange(chunk, dtype=targets.dtype)[None, :]
        ) & in_chunk[:, None]
        dl32 = (p - onehot.astype(jnp.float32)) * g[:, None]
        dlogits = dl32.astype(x.dtype)
        wc = jax.lax.dynamic_slice_in_dim(w, k * chunk, chunk, axis=1)
        dx = dx + jax.lax.dot_general(
            dlogits,
            wc.astype(x.dtype),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dwc = jax.lax.dot_general(
            x,
            dlogits,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dw = jax.lax.dynamic_update_slice_in_dim(dw, dwc, k * chunk, axis=1)
        if with_bias:
            db = jax.lax.dynamic_update_slice_in_dim(
                db, dl32.sum(axis=0), k * chunk, axis=0
            )
        return (dx, dw, db), None

    init = (
        jnp.zeros((n, d), jnp.float32),
        jnp.zeros((d, v), jnp.float32),
        jnp.zeros((v,), jnp.float32),
    )
    (dx, dw, db), _ = jax.lax.scan(body, init, jnp.arange(k_chunks))
    return (
        dx.astype(x.dtype),
        dw.astype(w.dtype),
        db.astype(b.dtype) if with_bias else None,
    )


# ---------------------------------------------------------------------------
# public entry points (two custom_vjps, one implementation)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_softmax_cross_entropy(x, w, targets, n_chunks=None):
    """Per-token cross-entropy ``logsumexp(x@w) - (x@w)[target]``.

    Args:
      x: ``(N, d)`` activations (bf16 recommended; matmuls run in ``x.dtype``
        with fp32 accumulation).
      w: ``(d, V)`` classifier weights (cast to ``x.dtype`` for the matmul).
      targets: ``(N,)`` int32 class ids.
      n_chunks: vocab chunk count (must divide V); None = auto.

    Returns:
      ``(N,)`` fp32 per-token losses. ``jnp.mean`` of this equals the naive
      ``-log_softmax(x @ w)[target]`` mean up to input-dtype rounding.
    """
    losses, _ = _ce_forward(x, w, None, targets, _pick_chunks(w.shape[1], n_chunks))
    return losses


def _fwd(x, w, targets, n_chunks):
    losses, (x, w, _b, targets, lse, logits16) = _ce_fwd(x, w, None, targets, n_chunks)
    return losses, (x, w, targets, lse, logits16)


def _bwd(n_chunks, res, g):
    x, w, targets, lse, logits16 = res
    dx, dw, _db = _ce_bwd(n_chunks, (x, w, None, targets, lse, logits16), g)
    return dx, dw, None


fused_softmax_cross_entropy.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_softmax_cross_entropy_bias(x, w, b, targets, n_chunks=None):
    """``fused_softmax_cross_entropy`` with a differentiable (V,) logit
    bias (GPT-J's untied lm head): loss = logsumexp(x@w + b) - (x@w + b)[t]."""
    losses, _ = _ce_forward(x, w, b, targets, _pick_chunks(w.shape[1], n_chunks))
    return losses


def _fwd_bias(x, w, b, targets, n_chunks):
    return _ce_fwd(x, w, b, targets, n_chunks)


def _bwd_bias(n_chunks, res, g):
    dx, dw, db = _ce_bwd(n_chunks, res, g)
    return dx, dw, db, None


fused_softmax_cross_entropy_bias.defvjp(_fwd_bias, _bwd_bias)
