"""Causal multi-head attention.

Baseline path is pure XLA (einsum + online softmax is fused well by the TPU
compiler for moderate sequence lengths); a Pallas flash-attention kernel and
the ring-attention sequence-parallel variant plug in behind the same
signature. Reference framework has no attention op of its own (compute is
user torch code); this is part of the "long-context first-class" mandate
(SURVEY.md §5.7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """q,k,v: (batch, heads, seq, head_dim) → (batch, heads, seq, head_dim).

    Computed in bf16 with fp32 softmax accumulation (MXU-friendly); the causal
    mask is applied as an additive bias so XLA keeps one fused loop.
    """
    *_, seq, head_dim = q.shape
    scale = 1.0 / (head_dim**0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    mask = jnp.tril(jnp.ones((seq, seq), dtype=bool))
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)
