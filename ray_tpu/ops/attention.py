"""Causal multi-head attention — impl dispatcher.

Three interchangeable paths behind one signature (the reference framework has
no attention op of its own — compute is user torch code; this is part of the
"long-context first-class" mandate, SURVEY.md §5.7):

* ``xla``   — einsum + masked softmax; fine for short sequences, O(seq²)
  memory (the mask/score matrix materializes).
* ``flash`` — Pallas blockwise online-softmax kernel with custom-VJP
  backward (``ops/flash_attention.py``); O(seq) memory, MXU-dense.
* ``ring``  — sequence-parallel flash over the ``sp`` mesh axis
  (``ops/ring_attention.py``), selected by the model layer when the mesh
  shards sequence.

``auto`` picks flash whenever the shape tiles cleanly (TPU: always for the
model shapes here; other backends run the same kernels interpreted).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _xla_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    *_, seq, head_dim = q.shape
    scale = 1.0 / (head_dim**0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    mask = jnp.tril(jnp.ones((seq, seq), dtype=bool))
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, impl: str = "auto"
) -> jax.Array:
    """q,k,v: (batch, heads, seq, head_dim) → (batch, heads, seq, head_dim).

    bf16-friendly with fp32 softmax accumulation on every path.
    """
    if impl not in ("auto", "xla", "flash"):
        raise ValueError(
            f"unknown attention impl {impl!r}; expected 'auto', 'xla' or 'flash' "
            "(sequence-parallel ring attention is ops.ring_attention, selected "
            "by the model layer when the mesh shards sequence)"
        )
    if impl == "xla":
        return _xla_attention(q, k, v)
    seq = q.shape[2]
    if impl == "auto":
        from ray_tpu.ops.flash_attention import _interpret

        if seq < 128 or seq % 128 or _interpret():
            # ragged shapes can't tile the Pallas grid, and off-TPU the
            # kernel would run interpreted (orders of magnitude slower than
            # compiled XLA) — auto only picks flash where it wins
            return _xla_attention(q, k, v)
    from ray_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v)
