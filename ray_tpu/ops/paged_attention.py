"""Paged attention over a block-table KV cache: decode, prefill, verify.

Generalizes ``models.gptj._attend_cached`` (one query row against a dense
per-sequence cache) to the paged layout the ``ray_tpu.llm`` engine uses:
the cluster-wide KV cache is a fixed pool of physical blocks

    k_pool, v_pool : (num_blocks, heads, block_size, head_dim)

and each decode slot owns a *block table* mapping its logical block index
to a physical block id.  Static shapes throughout — the pool size, block
size, and table width are compile-time constants; only the table CONTENTS
and per-slot lengths are data — so the engine jits one decode step and
reuses it for every admission/eviction pattern.

Three entry points:

* ``paged_attention`` — one query per slot (the decode step).
* ``paged_prefill_attention_xla`` — chunked prefill for ONE sequence.
* ``paged_verify_attention`` — ``w = k+1`` consecutive queries per slot
  (speculative-decode verification): query ``i`` of a slot sits at
  ``positions[s, i]`` and attends causally over the slot's paged cache
  INCLUDING the window's own earlier positions (their k/v are scattered
  in before the attention runs).  The causal intra-window mask is just
  ``cache_pos <= positions[s, i]`` — window k/v live at those positions.

``paged_attention`` and ``paged_verify_attention`` each have two
interchangeable paths behind one signature (same contract as
``ops.attention``):

* ``xla``    — gather the table's blocks into a dense (slots, heads,
  table*block, d) view, masked softmax.  The reference path; also what
  multi-chip pjit partitions cleanly.
* ``pallas`` — a scalar-prefetch Pallas kernel: grid (slot, logical
  block), the block table is prefetched so each step DMAs exactly its
  physical KV block from HBM, online-softmax accumulation across the
  minor (block) grid dimension.  No (slots, table*block) score matrix
  and no gathered cache copy ever materializes.  Runs interpreted
  off-TPU so CPU CI exercises the same code path (parity tests:
  ``tests/test_llm_engine.py``, ``tests/test_llm_spec.py``).

``auto`` picks the Pallas kernel on TPU when the shapes tile the MXU
(block_size a multiple of 8, head_dim of 128), else XLA.

Convention: table entries past a sequence's allocation MUST point at a
valid physical block (the engine pads with block 0, its reserved trash
block); masking by ``lengths``/``positions`` makes their values
irrelevant.  Slots with ``length == 0`` produce finite garbage
(big-negative masking, never NaN) — callers discard inactive slots.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


#: RL022-verified registry of pallas wrappers whose COMPILED path is
#: currently unexercised: the auto dispatcher routes to the XLA path
#: wherever ``_interpret()`` is on — i.e. exactly where CI runs — so the
#: kernels below have zero compiled-TPU validation coverage. Each entry
#: is acknowledged validation debt (the ROADMAP's real-TPU tiling
#: validation item); un-gating a kernel makes its entry stale and the
#: lint forces it to be retired with the debt.
INTERPRET_ONLY = (
    "_paged_pallas: decode kernel's MXU tiling (block_size % 8, d % 128)"
    " is unvalidated on real TPUs — auto dispatch falls back to XLA"
    " off-TPU (ROADMAP real-TPU validation item)",
    "_paged_verify_pallas: verify kernel rides the same gating; the"
    " small window dim's tiling is unvalidated on real TPUs (ROADMAP"
    " real-TPU validation item)",
)

# Tensor-parallel (llm.multichip) tiling notes for the real-TPU
# follow-up.  Under ``EngineConfig(tp=N)`` these kernels run INSIDE a
# shard_map body: the pool and query tensors they see carry
# ``n_heads // tp`` LOCAL heads (the head axis is sharded
# ``P(None, None, "tp", None, None)``), everything else — block_size,
# head_dim, the block tables — is unchanged.  Consequences for the
# compiled path when the gates above are retired:
#   * the MXU constraints are per-head (block_size % 8, head_dim % 128),
#     so head-sharding does not change any tile shape — a kernel that
#     tiles at tp=1 tiles at any tp;
#   * the head axis is the kernel grid's embarrassingly-parallel dim;
#     shrinking it tp-fold shrinks the grid, so per-device occupancy
#     drops for configs with few heads (e.g. 8 heads at tp=4 leaves a
#     2-wide grid) — prefer fusing heads into the batch grid dim before
#     validating small-head configs;
#   * no collective runs inside the kernel: the tp psum happens in the
#     caller (multichip._tp_layer) AFTER the attention output
#     projection, so the Pallas body needs no REMOTE dma / barrier
#     semantics and interpret-mode parity on host devices remains a
#     faithful oracle for the sharded path.


# ---------------------------------------------------------------------------
# XLA reference path
# ---------------------------------------------------------------------------


def paged_attention_xla(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """q: (slots, heads, d); pools: (num_blocks, heads, block, d);
    block_tables: (slots, tmax) int32; lengths: (slots,) int32 — valid
    cache positions per slot (new token's k/v already written).
    Returns (slots, heads, d) in q.dtype, fp32 softmax accumulation."""
    s, h, d = q.shape
    scale = d**-0.5
    k = k_pool[block_tables]  # (slots, tmax, heads, block, d)
    v = v_pool[block_tables]
    k = k.transpose(0, 2, 1, 3, 4).reshape(s, h, -1, d)
    v = v.transpose(0, 2, 1, 3, 4).reshape(s, h, -1, d)
    logits = jnp.einsum(
        "shd,shkd->shk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    mask = jnp.arange(k.shape[2])[None, None, :] < lengths[:, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("shk,shkd->shd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_prefill_attention_xla(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_table: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Chunked-prefill attention for ONE sequence: each chunk query at
    ``positions[i]`` attends causally over the sequence's paged cache
    (chunk k/v already scattered in).  q: (chunk, heads, d);
    block_table: (tmax,) int32; positions: (chunk,) int32.  Returns
    (chunk, heads, d)."""
    c, h, d = q.shape
    scale = d**-0.5
    k = k_pool[block_table]  # (tmax, heads, block, d)
    v = v_pool[block_table]
    k = k.transpose(1, 0, 2, 3).reshape(h, -1, d)
    v = v.transpose(1, 0, 2, 3).reshape(h, -1, d)
    logits = jnp.einsum(
        "chd,hkd->chk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    mask = jnp.arange(k.shape[1])[None, None, :] <= positions[:, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("chk,hkd->chd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_verify_attention_xla(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Multi-query verification attention (see module doc).  q: (slots, w,
    heads, d); pools: (num_blocks, heads, block, d); block_tables:
    (slots, tmax) int32; positions: (slots, w) int32 — the absolute cache
    position of each query (its own k/v already written).  Query (s, i)
    attends every cache position ``<= positions[s, i]`` — causal across
    the window because the window's positions are consecutive.  Returns
    (slots, w, heads, d) in q.dtype, fp32 softmax accumulation."""
    s, w, h, d = q.shape
    scale = d**-0.5
    k = k_pool[block_tables]  # (slots, tmax, heads, block, d)
    v = v_pool[block_tables]
    k = k.transpose(0, 2, 1, 3, 4).reshape(s, h, -1, d)
    v = v.transpose(0, 2, 1, 3, 4).reshape(s, h, -1, d)
    logits = jnp.einsum(
        "swhd,shkd->swhk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    mask = (
        jnp.arange(k.shape[2])[None, None, None, :]
        <= positions[:, :, None, None]
    )
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("swhk,shkd->swhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _paged_kernel(
    # scalar prefetch
    tables_ref,   # (slots * tmax,) int32 — flattened block tables
    lengths_ref,  # (slots,) int32
    # blocked inputs
    q_ref,        # (1, heads, d)
    k_ref,        # (1, heads, block, d) — THE slot's j-th physical block
    v_ref,
    # blocked output
    o_ref,        # (1, heads, d)
    # scratch (carried across the minor grid dim)
    acc_ref,      # (heads, d) f32
    m_ref,        # (heads, 1) f32
    l_ref,        # (heads, 1) f32
    *,
    block_size: int,
    scale: float,
):
    s = pl.program_id(0)
    j = pl.program_id(1)
    n_blocks = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lengths_ref[s]

    @pl.when(j * block_size < length)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)            # (heads, d)
        k = k_ref[0].astype(jnp.float32)            # (heads, block, d)
        v = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k,
            (((1,), (2,)), ((0,), (0,))),           # contract d, batch heads
            preferred_element_type=jnp.float32,
        ) * scale                                    # (heads, block)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        scores = jnp.where(pos < length, scores, NEG_INF)

        m_prev = m_ref[...]                          # (heads, 1)
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)              # (heads, 1)
        p = jnp.exp(scores - m_new)                  # (heads, block)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            (((1,), (1,)), ((0,), (0,))),            # contract block, batch heads
            preferred_element_type=jnp.float32,
        )                                            # (heads, d)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new

    @pl.when(j == n_blocks - 1)
    def _flush():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _paged_pallas(q, k_pool, v_pool, block_tables, lengths):
    slots, heads, d = q.shape
    _, _, block_size, _ = k_pool.shape
    tmax = block_tables.shape[1]
    scale = d**-0.5
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        # minor (block) dimension executes sequentially on TPU, so the
        # online-softmax scratch carries across a slot's kv blocks
        grid=(slots, tmax),
        in_specs=[
            pl.BlockSpec((1, heads, d), lambda s, j, tbl, lens: (s, 0, 0)),
            pl.BlockSpec(
                (1, heads, block_size, d),
                lambda s, j, tbl, lens: (tbl[s * tmax + j], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, heads, block_size, d),
                lambda s, j, tbl, lens: (tbl[s * tmax + j], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, heads, d), lambda s, j, tbl, lens: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((heads, d), jnp.float32),
            pltpu.VMEM((heads, 1), jnp.float32),
            pltpu.VMEM((heads, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, block_size=block_size, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, heads, d), q.dtype),
        interpret=_interpret(),
    )(block_tables.reshape(-1).astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pool, v_pool)


def _paged_verify_kernel(
    # scalar prefetch
    tables_ref,   # (slots * tmax,) int32 — flattened block tables
    pos_ref,      # (slots * w,) int32 — flattened query positions
    # blocked inputs
    q_ref,        # (1, w, heads, d)
    k_ref,        # (1, heads, block, d) — THE slot's j-th physical block
    v_ref,
    # blocked output
    o_ref,        # (1, w, heads, d)
    # scratch (carried across the minor grid dim)
    acc_ref,      # (heads, w, d) f32
    m_ref,        # (heads, w, 1) f32
    l_ref,        # (heads, w, 1) f32
    *,
    block_size: int,
    w: int,
    scale: float,
):
    s = pl.program_id(0)
    j = pl.program_id(1)
    n_blocks = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # per-query positions; the window is consecutive, so the LAST query's
    # position bounds the valid cache
    qpos = jnp.stack([pos_ref[s * w + i] for i in range(w)])  # (w,)
    length = qpos[w - 1] + 1

    @pl.when(j * block_size < length)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32).transpose(1, 0, 2)  # (heads, w, d)
        k = k_ref[0].astype(jnp.float32)                     # (heads, block, d)
        v = v_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k,
            (((2,), (2,)), ((0,), (0,))),    # contract d, batch heads
            preferred_element_type=jnp.float32,
        ) * scale                             # (heads, w, block)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )                                     # (1, block)
        causal = pos[None, :, :] <= qpos[None, :, None]  # (1, w, block)
        scores = jnp.where(causal, scores, NEG_INF)

        m_prev = m_ref[...]                   # (heads, w, 1)
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)           # (heads, w, block)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v,
            (((2,), (1,)), ((0,), (0,))),     # contract block, batch heads
            preferred_element_type=jnp.float32,
        )                                     # (heads, w, d)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = m_new

    @pl.when(j == n_blocks - 1)
    def _flush():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).transpose(1, 0, 2).astype(o_ref.dtype)


def _paged_verify_pallas(q, k_pool, v_pool, block_tables, positions):
    slots, w, heads, d = q.shape
    _, _, block_size, _ = k_pool.shape
    tmax = block_tables.shape[1]
    scale = d**-0.5
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, tmax),
        in_specs=[
            pl.BlockSpec((1, w, heads, d), lambda s, j, tbl, pos: (s, 0, 0, 0)),
            pl.BlockSpec(
                (1, heads, block_size, d),
                lambda s, j, tbl, pos: (tbl[s * tmax + j], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, heads, block_size, d),
                lambda s, j, tbl, pos: (tbl[s * tmax + j], 0, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, w, heads, d), lambda s, j, tbl, pos: (s, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((heads, w, d), jnp.float32),
            pltpu.VMEM((heads, w, 1), jnp.float32),
            pltpu.VMEM((heads, w, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _paged_verify_kernel, block_size=block_size, w=w, scale=scale
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, w, heads, d), q.dtype),
        interpret=_interpret(),
    )(block_tables.reshape(-1).astype(jnp.int32),
      positions.reshape(-1).astype(jnp.int32),
      q, k_pool, v_pool)


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    impl: str = "auto",
) -> jax.Array:
    """Single-position attention over a paged KV cache (see module doc).

    q: (slots, heads, head_dim); k_pool/v_pool: (num_blocks, heads,
    block_size, head_dim); block_tables: (slots, tmax) int32; lengths:
    (slots,) int32.  ``impl``: auto | xla | pallas.
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"unknown paged attention impl {impl!r}; expected 'auto', 'xla' "
            "or 'pallas'"
        )
    if impl == "xla":
        return paged_attention_xla(q, k_pool, v_pool, block_tables, lengths)
    if impl == "auto":
        _, _, block_size, d = k_pool.shape
        # off-TPU the kernel would run interpreted (orders of magnitude
        # slower than compiled XLA); on TPU it needs MXU-friendly tiling
        if _interpret() or block_size % 8 or d % 128:
            return paged_attention_xla(q, k_pool, v_pool, block_tables, lengths)
    return _paged_pallas(q, k_pool, v_pool, block_tables, lengths)


def paged_verify_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    impl: str = "auto",
) -> jax.Array:
    """Multi-query verification attention over a paged KV cache (see
    module doc): ``w`` consecutive queries per slot for speculative-decode
    verification, causal intra-window masking by absolute position.

    q: (slots, w, heads, head_dim); k_pool/v_pool: (num_blocks, heads,
    block_size, head_dim); block_tables: (slots, tmax) int32; positions:
    (slots, w) int32.  ``impl``: auto | xla | pallas.
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"unknown paged attention impl {impl!r}; expected 'auto', 'xla' "
            "or 'pallas'"
        )
    if impl == "xla":
        return paged_verify_attention_xla(q, k_pool, v_pool, block_tables, positions)
    if impl == "auto":
        _, _, block_size, d = k_pool.shape
        # same gating as paged_attention; real-TPU tiling of the small
        # window dim rides the same validation item (ROADMAP)
        if _interpret() or block_size % 8 or d % 128:
            return paged_verify_attention_xla(
                q, k_pool, v_pool, block_tables, positions
            )
    return _paged_verify_pallas(q, k_pool, v_pool, block_tables, positions)
