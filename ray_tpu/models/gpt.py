"""Decoder-only transformer (GPT family) — the flagship model.

Matches the reference's north-star workload (GPT-J-6B fine-tune,
BASELINE.json / ``release/air_examples/gptj_deepspeed_finetuning``) but built
TPU-first:

* parameters are a plain pytree with the layer dimension stacked in front, so
  the depth loop is one ``lax.scan`` (constant compile time in depth) with
  ``jax.checkpoint`` rematerialization per block (HBM ∝ 1 layer of
  activations);
* compute in bfloat16 on the MXU, params kept fp32 (master copy) and cast at
  use; fp32 softmax/layernorm accumulations;
* no data-dependent Python control flow — everything jits once;
* sharding is external: ``ray_tpu.parallel.sharding`` maps parameter paths to
  PartitionSpecs; this file only places activation constraints.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import causal_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50_304          # multiple of 128 for MXU lanes
    seq_len: int = 1024
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    dtype: str = "bfloat16"           # activation/compute dtype
    remat: bool = True
    #: "full" recomputes the whole block in backward (min HBM);
    #: "dots" saves matmul outputs (recomputes only cheap elementwise —
    #: more HBM, fewer backward FLOPs); "attn" saves only the attention
    #: output (skips recomputing flash attention, the priciest recompute,
    #: at one (b,s,d) tensor per layer); "big" saves attention + MLP
    #: hidden. Tune per chip generation.
    remat_policy: str = "full"
    #: Blockwise fused cross-entropy in gpt_loss: never materializes the
    #: (tokens, vocab) logits (the largest HBM consumer at bench shapes)
    #: and runs the lm-head matmuls in the activation dtype on the MXU.
    fused_loss: bool = True
    #: Vocab chunk count for the fused loss (None = memory-conservative
    #: auto). 1 = one full-width pass: fastest when HBM headroom allows the
    #: (tokens, vocab) fp32 transient (round-5 v5e sweep: chunks=1 beat the
    #: 3-chunk auto by ~1 MFU point at the 406M bench shape).
    ce_chunks: Optional[int] = None
    attn_impl: str = "auto"           # auto|xla|flash|ring (see ops/attention)
    #: lax.scan unroll over the layer dimension: >1 lets XLA schedule across
    #: block boundaries (overlap the next layer's weight loads with this
    #: layer's math) at the cost of compile time ∝ unroll
    scan_unroll: int = 1
    # Mixture-of-Experts (0 = dense MLP). Experts shard over the mesh's
    # ``ep`` axis; routing uses GShard/Switch-style dense dispatch einsums
    # (one-hot matmuls — static shapes, MXU-friendly, XLA inserts the
    # all-to-alls from the sharding constraints).
    n_experts: int = 0
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01      # load-balancing loss weight

    # GPT-J-6B shape (reference north star):
    # vocab 50400→50432, seq 2048, d_model 4096, 28 layers, 16 heads

    @property
    def d_ff(self) -> int:
        return 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def gpt_init(rng: jax.Array, cfg: GPTConfig) -> dict:
    """Initialize the parameter pytree (fp32 master weights)."""
    k_tok, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
    d, dff, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    init = jax.nn.initializers.normal(0.02)

    def kernel(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)

    ks = jax.random.split(k_blocks, 5)
    blocks = {
        "ln1": {"scale": jnp.ones((L, d)), "bias": jnp.zeros((L, d))},
        "attn_qkv": {"kernel": kernel(ks[0], (L, d, 3 * d), d), "bias": jnp.zeros((L, 3 * d))},
        "attn_out": {"kernel": kernel(ks[1], (L, d, d), d), "bias": jnp.zeros((L, d))},
        "ln2": {"scale": jnp.ones((L, d)), "bias": jnp.zeros((L, d))},
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        blocks["router"] = {"kernel": kernel(ks[4], (L, d, E), d)}
        blocks["moe_in"] = {"kernel": kernel(ks[2], (L, E, d, dff), d)}
        blocks["moe_out"] = {"kernel": kernel(ks[3], (L, E, dff, d), dff)}
    else:
        blocks["mlp_in"] = {"kernel": kernel(ks[2], (L, d, dff), d), "bias": jnp.zeros((L, dff))}
        blocks["mlp_out"] = {"kernel": kernel(ks[3], (L, dff, d), dff), "bias": jnp.zeros((L, d))}
    return {
        "embed": {
            "tokens": init(k_tok, (cfg.vocab_size, d), jnp.float32),
            "pos": init(k_pos, (cfg.seq_len, d), jnp.float32),
        },
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "lm_head": {"kernel": kernel(k_head, (d, cfg.vocab_size), d)},
    }


def _layernorm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias
    return out.astype(x.dtype)


def _moe_mlp(cfg: GPTConfig, x, layer, c):
    """Mixture-of-experts MLP with GShard/Switch dense dispatch.

    Routing is all one-hot einsums over static shapes: top-k gate → capacity
    assignment via cumsum → (tokens, E, cap) dispatch tensor → expert matmuls
    on (E, cap, d) — sharded over the ``ep`` mesh axis, so XLA compiles the
    dispatch/combine einsums into all-to-alls over ICI. Over-capacity
    assignments drop (standard). Returns (out, aux_loss).
    """
    from jax.sharding import PartitionSpec as P

    b, s, d = x.shape
    E, k = cfg.n_experts, cfg.experts_per_token
    n = b * s
    cap = max(1, int(cfg.capacity_factor * k * n / E))
    flat = x.reshape(n, d)

    logits = (flat @ layer["router"]["kernel"].astype(x.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                     # (n, E)
    gate_w, gate_idx = jax.lax.top_k(probs, k)                  # (n, k)
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)

    # (n*k assignments) -> expert one-hot, position within expert via cumsum
    a_idx = gate_idx.reshape(n * k)
    onehot = jax.nn.one_hot(a_idx, E, dtype=jnp.float32)        # (nk, E)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # (nk, E)
    pos_in_expert = pos.sum(-1)                                 # (nk,)
    keep = (pos_in_expert < cap).astype(jnp.float32)
    disp = onehot * keep[:, None]                               # (nk, E)
    pos_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), cap, dtype=jnp.float32)
    # fold the k slots back into tokens: (n, E, cap) dispatch tensor — a
    # token's top-k experts are distinct, so summing slots never collides.
    # O(n·E·cap), never an (n, n) tensor (GShard's dispatch/combine form).
    disp_t = (disp[:, :, None] * pos_oh[:, None, :]).reshape(n, k, E, cap)
    dispatch = disp_t.sum(axis=1)                               # (n, E, cap)
    combine = (disp_t * gate_w[:, :, None, None]).sum(axis=1)   # (n, E, cap)

    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), flat)
    expert_in = c(expert_in, P("ep", None, None))
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, layer["moe_in"]["kernel"].astype(x.dtype))
    )
    h = c(h, P("ep", None, "tp"))
    expert_out = jnp.einsum("ecf,efd->ecd", h, layer["moe_out"]["kernel"].astype(x.dtype))
    expert_out = c(expert_out, P("ep", None, None))
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out).reshape(b, s, d)

    # Switch load-balancing aux: E * sum(frac_tokens_e * mean_prob_e)
    frac = (onehot * keep[:, None]).mean(0)
    mean_prob = probs.mean(0)
    aux = E * jnp.sum(frac * mean_prob) * k
    return out, aux.astype(jnp.float32)


def _block(cfg: GPTConfig, x, layer, mesh=None):
    """One transformer block. ``layer`` = this layer's params (leading L dim
    already indexed away by scan)."""
    from jax.sharding import PartitionSpec as P

    def c(y, spec):
        if mesh is None:
            return y
        from ray_tpu.parallel.sharding import constrain

        return constrain(y, mesh, spec)

    dt = x.dtype
    b, s, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim

    ln1 = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
    qkv = ln1 @ layer["attn_qkv"]["kernel"].astype(dt) + layer["attn_qkv"]["bias"].astype(dt)
    qkv = checkpoint_name(qkv, "qkv")  # saved only under remat_policy="attn_qkv"
    # seq stays sharded over sp end-to-end (sequence parallelism); sp=1
    # meshes make these the same constraints as before.
    qkv = c(qkv, P(("dp", "fsdp"), "sp", "tp"))
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, h, hd).transpose(0, 2, 1, 3)

    impl = cfg.attn_impl
    if impl == "ring" and mesh is None:
        raise ValueError(
            "attn_impl='ring' needs a device mesh with an 'sp' axis; pass "
            "mesh= (or use attn_impl='auto', which picks ring only when the "
            "mesh shards sequence)"
        )
    if impl == "ring" or (
        impl == "auto" and mesh is not None and mesh.shape.get("sp", 1) > 1
    ):
        # sequence sharded over sp: ring attention rotates KV over ICI
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        att = ring_attention_sharded(heads(q), heads(k), heads(v), mesh)
    else:
        from ray_tpu.ops.flash_attention import _interpret, flash_shardable

        want_flash = impl == "flash" or (impl == "auto" and not _interpret())
        if (
            want_flash
            and mesh is not None
            and mesh.size > 1
            and s >= 128
            and s % 128 == 0
            and flash_shardable(b, h, mesh)
        ):
            # multi-device pjit: shard_map the Pallas kernel so it runs on
            # each chip's dp/tp shard instead of being replicated (no GSPMD
            # rule for a bare pallas_call)
            from ray_tpu.ops.flash_attention import flash_attention_sharded

            att = flash_attention_sharded(heads(q), heads(k), heads(v), mesh)
        elif want_flash and mesh is not None and mesh.size > 1:
            # multi-device but not shardable (batch/heads don't divide the
            # mesh): a bare pallas_call would replicate on every chip — the
            # XLA einsum partitions correctly instead
            att = causal_attention(heads(q), heads(k), heads(v), impl="xla")
        else:
            att = causal_attention(heads(q), heads(k), heads(v), impl=impl)
    att = att.transpose(0, 2, 1, 3).reshape(b, s, d)
    att = att @ layer["attn_out"]["kernel"].astype(dt) + layer["attn_out"]["bias"].astype(dt)
    x = x + c(att, P(("dp", "fsdp"), "sp", None))

    ln2 = _layernorm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
    if cfg.n_experts > 0:
        out, aux = _moe_mlp(cfg, ln2, layer, c)
    else:
        hmid = jax.nn.gelu(ln2 @ layer["mlp_in"]["kernel"].astype(dt) + layer["mlp_in"]["bias"].astype(dt))
        hmid = checkpoint_name(hmid, "mlp_mid")
        hmid = c(hmid, P(("dp", "fsdp"), "sp", "tp"))
        out = hmid @ layer["mlp_out"]["kernel"].astype(dt) + layer["mlp_out"]["bias"].astype(dt)
        aux = jnp.float32(0.0)
    return x + c(out, P(("dp", "fsdp"), "sp", None)), aux


_REMAT_POLICIES = {
    "full": lambda: None,
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    # "attn" keeps the flash kernel's out+lse (tagged inside _flash_core_fwd)
    # so the backward's rematerialization never re-runs the attention kernel
    # — everything else (layernorms, qkv/mlp matmuls) recomputes as usual.
    # (On the non-flash XLA fallback there is nothing tagged, so these
    # degrade gracefully to full remat.)
    "attn": lambda: jax.checkpoint_policies.save_only_these_names(
        "flash_out", "flash_lse"
    ),
    # "attn_qkv" additionally saves the qkv projection ((b,s,3d) per layer):
    # the backward then recomputes only layernorms + the cheap elementwise
    # chain, not the qkv matmul feeding the attention VJP
    "attn_qkv": lambda: jax.checkpoint_policies.save_only_these_names(
        "flash_out", "flash_lse", "qkv"
    ),
    "big": lambda: jax.checkpoint_policies.save_only_these_names(
        "flash_out", "flash_lse", "mlp_mid"
    ),
}


def gpt_hidden(cfg: GPTConfig, params: dict, tokens: jax.Array, mesh=None):
    """tokens (batch, seq) int32 → (final hidden (batch, seq, d_model) in the
    activation dtype, mean MoE aux loss). The lm head is applied by the
    caller — gpt_forward materializes logits; gpt_loss feeds the hidden to
    the blockwise fused cross-entropy instead."""
    dt = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    # gather fp32 rows THEN cast: casting the whole (vocab, d) table first
    # would stream 50k rows through the VPU to use 24k
    x = params["embed"]["tokens"][tokens].astype(dt)
    x = x + params["embed"]["pos"][:s].astype(dt)

    def block(carry, layer):
        y, aux = _block(cfg, carry, layer, mesh)
        return y, aux

    if cfg.remat:
        if cfg.remat_policy not in _REMAT_POLICIES:
            raise ValueError(
                f"remat_policy must be one of {sorted(_REMAT_POLICIES)}, "
                f"got {cfg.remat_policy!r}"
            )
        policy = _REMAT_POLICIES[cfg.remat_policy]()
        block = jax.checkpoint(block, prevent_cse=False, policy=policy)
    x, auxes = jax.lax.scan(block, x, params["blocks"], unroll=cfg.scan_unroll)

    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return x, auxes.mean()


def gpt_forward(
    cfg: GPTConfig, params: dict, tokens: jax.Array, mesh=None, return_aux: bool = False
):
    """tokens (batch, seq) int32 → logits (batch, seq, vocab) fp32.

    ``return_aux=True`` also returns the mean MoE load-balancing loss."""
    x, aux = gpt_hidden(cfg, params, tokens, mesh)
    logits = x.astype(jnp.float32) @ params["lm_head"]["kernel"]
    if return_aux:
        return logits, aux
    return logits


def gpt_loss(cfg: GPTConfig, params: dict, tokens: jax.Array, mesh=None) -> jax.Array:
    """Next-token cross-entropy, mean over (batch, seq-1); MoE configs add
    the weighted load-balancing aux loss.

    With ``cfg.fused_loss`` (default) the loss never materializes the
    (tokens, vocab) logits: ``ops.fused_ce`` streams vocab chunks through
    the MXU in the activation dtype (see its module docstring for the HBM
    arithmetic — ~6.6 GB saved at the 406M bench shape)."""
    hidden, aux = gpt_hidden(cfg, params, tokens[:, :-1], mesh)
    targets = tokens[:, 1:]
    if cfg.fused_loss:
        from ray_tpu.ops.fused_ce import fused_softmax_cross_entropy

        b, s, d = hidden.shape
        losses = fused_softmax_cross_entropy(
            hidden.reshape(b * s, d),
            params["lm_head"]["kernel"],
            targets.reshape(-1).astype(jnp.int32),
            cfg.ce_chunks,
        )
        loss = losses.mean()
    else:
        logits = hidden.astype(jnp.float32) @ params["lm_head"]["kernel"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -ll.mean()
    if cfg.n_experts > 0:
        loss = loss + cfg.moe_aux_weight * aux
    return loss


# ---------------------------------------------------------------------------
# KV-cache decode (inference path; sampling shared with models.sampling)
# ---------------------------------------------------------------------------


def gpt_decode(
    cfg: GPTConfig,
    params: dict,
    prompt: jax.Array,
    n_new: int,
    *,
    key: Optional[jax.Array] = None,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """Decode ``n_new`` tokens after ``prompt`` (b, s0) int32 →
    (b, s0 + n_new), KV-cached with static shapes (same discipline as
    ``models.gptj.gptj_decode``: one prefill forward capturing per-layer
    k/v, then a ``lax.fori_loop`` of single-position steps). Greedy by
    default; with a PRNG ``key``, per-token temperature/top-k/top-p via
    ``models.sampling.sample_tokens`` (scalars or per-row arrays).

    Dense blocks only (``n_experts == 0``); the learned positional table
    caps ``s0 + n_new`` at ``cfg.seq_len``."""
    if cfg.n_experts > 0:
        raise NotImplementedError("gpt_decode supports dense (non-MoE) configs only")
    dt = jnp.dtype(cfg.dtype)
    b, s0 = prompt.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    max_len = s0 + n_new
    if max_len > cfg.seq_len:
        raise ValueError(
            f"prompt ({s0}) + n_new ({n_new}) exceeds the positional table "
            f"(seq_len={cfg.seq_len})"
        )

    def pick(logits, step_idx):
        if key is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        from ray_tpu.models.sampling import sample_tokens

        return sample_tokens(
            logits, jax.random.fold_in(key, step_idx), temperature, top_k, top_p
        )

    def heads(t, s):
        return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

    # ---- prefill: normal stacked forward, capturing per-layer k/v
    x = params["embed"]["tokens"][prompt].astype(dt)
    x = x + params["embed"]["pos"][:s0].astype(dt)

    def prefill_block(carry, layer):
        ln1 = _layernorm(carry, layer["ln1"]["scale"], layer["ln1"]["bias"])
        qkv = ln1 @ layer["attn_qkv"]["kernel"].astype(dt) + layer["attn_qkv"]["bias"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = causal_attention(heads(q, s0), heads(k, s0), heads(v, s0), impl="xla")
        att = att.transpose(0, 2, 1, 3).reshape(b, s0, cfg.d_model)
        att = att @ layer["attn_out"]["kernel"].astype(dt) + layer["attn_out"]["bias"].astype(dt)
        h = carry + att
        ln2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
        mid = jax.nn.gelu(
            ln2 @ layer["mlp_in"]["kernel"].astype(dt) + layer["mlp_in"]["bias"].astype(dt)
        )
        mlp = mid @ layer["mlp_out"]["kernel"].astype(dt) + layer["mlp_out"]["bias"].astype(dt)
        pad = jnp.zeros((b, nh, n_new, hd), dt)
        kc = jnp.concatenate([heads(k, s0).astype(dt), pad], axis=2)
        vc = jnp.concatenate([heads(v, s0).astype(dt), pad], axis=2)
        return h + mlp, (kc, vc)

    x, (k_caches, v_caches) = jax.lax.scan(prefill_block, x, params["blocks"])
    hlast = _layernorm(x[:, -1], params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = hlast.astype(jnp.float32) @ params["lm_head"]["kernel"]
    first_new = pick(logits, 0)  # (b,)

    tokens = jnp.concatenate([prompt, jnp.zeros((b, n_new), jnp.int32)], axis=1)
    tokens = jax.lax.dynamic_update_slice(tokens, first_new[:, None], (0, s0))

    def step(i, carry):
        tokens, k_caches, v_caches = carry
        pos = s0 + i  # position of the token being FED
        tok = jax.lax.dynamic_slice(tokens, (0, pos), (b, 1))[:, 0]
        x1 = params["embed"]["tokens"][tok].astype(dt)  # (b, d)
        x1 = x1 + jax.lax.dynamic_slice(
            params["embed"]["pos"], (pos, 0), (1, cfg.d_model)
        ).astype(dt)

        def one_layer(carry1, inputs):
            x1 = carry1
            layer, kc, vc = inputs
            ln1 = _layernorm(x1, layer["ln1"]["scale"], layer["ln1"]["bias"])
            qkv = ln1 @ layer["attn_qkv"]["kernel"].astype(dt) + layer["attn_qkv"]["bias"].astype(dt)
            q, k, v = jnp.split(qkv, 3, axis=-1)  # (b, d) each
            q = q.reshape(b, nh, hd)
            k = k.reshape(b, nh, 1, hd).astype(dt)
            v = v.reshape(b, nh, 1, hd).astype(dt)
            kc = jax.lax.dynamic_update_slice(kc, k, (0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(vc, v, (0, 0, pos, 0))
            from ray_tpu.models.gptj import _attend_cached

            att = _attend_cached(q, kc, vc, pos + 1).astype(dt)
            att = att.reshape(b, cfg.d_model) @ layer["attn_out"]["kernel"].astype(dt)
            att = att + layer["attn_out"]["bias"].astype(dt)
            h = x1 + att
            ln2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
            mid = jax.nn.gelu(
                ln2 @ layer["mlp_in"]["kernel"].astype(dt)
                + layer["mlp_in"]["bias"].astype(dt)
            )
            mlp = mid @ layer["mlp_out"]["kernel"].astype(dt) + layer["mlp_out"]["bias"].astype(dt)
            return h + mlp, (kc, vc)

        x1, (k_caches, v_caches) = jax.lax.scan(
            one_layer, x1, (params["blocks"], k_caches, v_caches)
        )
        h1 = _layernorm(x1, params["ln_f"]["scale"], params["ln_f"]["bias"])
        logits = h1.astype(jnp.float32) @ params["lm_head"]["kernel"]
        nxt = pick(logits, i + 1)
        tokens = jax.lax.dynamic_update_slice(tokens, nxt[:, None], (0, pos + 1))
        return tokens, k_caches, v_caches

    tokens, _, _ = jax.lax.fori_loop(0, n_new - 1, step, (tokens, k_caches, v_caches))
    return tokens
