"""Decoder-only transformer (GPT family) — the flagship model.

Matches the reference's north-star workload (GPT-J-6B fine-tune,
BASELINE.json / ``release/air_examples/gptj_deepspeed_finetuning``) but built
TPU-first:

* parameters are a plain pytree with the layer dimension stacked in front, so
  the depth loop is one ``lax.scan`` (constant compile time in depth) with
  ``jax.checkpoint`` rematerialization per block (HBM ∝ 1 layer of
  activations);
* compute in bfloat16 on the MXU, params kept fp32 (master copy) and cast at
  use; fp32 softmax/layernorm accumulations;
* no data-dependent Python control flow — everything jits once;
* sharding is external: ``ray_tpu.parallel.sharding`` maps parameter paths to
  PartitionSpecs; this file only places activation constraints.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import causal_attention


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50_304          # multiple of 128 for MXU lanes
    seq_len: int = 1024
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    dtype: str = "bfloat16"           # activation/compute dtype
    remat: bool = True
    attn_impl: str = "auto"           # auto|xla|flash|ring (see ops/attention)

    # GPT-J-6B shape (reference north star):
    # vocab 50400→50432, seq 2048, d_model 4096, 28 layers, 16 heads

    @property
    def d_ff(self) -> int:
        return 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def gpt_init(rng: jax.Array, cfg: GPTConfig) -> dict:
    """Initialize the parameter pytree (fp32 master weights)."""
    k_tok, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
    d, dff, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    init = jax.nn.initializers.normal(0.02)

    def kernel(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)

    ks = jax.random.split(k_blocks, 4)
    return {
        "embed": {
            "tokens": init(k_tok, (cfg.vocab_size, d), jnp.float32),
            "pos": init(k_pos, (cfg.seq_len, d), jnp.float32),
        },
        "blocks": {
            "ln1": {"scale": jnp.ones((L, d)), "bias": jnp.zeros((L, d))},
            "attn_qkv": {"kernel": kernel(ks[0], (L, d, 3 * d), d), "bias": jnp.zeros((L, 3 * d))},
            "attn_out": {"kernel": kernel(ks[1], (L, d, d), d), "bias": jnp.zeros((L, d))},
            "ln2": {"scale": jnp.ones((L, d)), "bias": jnp.zeros((L, d))},
            "mlp_in": {"kernel": kernel(ks[2], (L, d, dff), d), "bias": jnp.zeros((L, dff))},
            "mlp_out": {"kernel": kernel(ks[3], (L, dff, d), dff), "bias": jnp.zeros((L, d))},
        },
        "ln_f": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "lm_head": {"kernel": kernel(k_head, (d, cfg.vocab_size), d)},
    }


def _layernorm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias
    return out.astype(x.dtype)


def _block(cfg: GPTConfig, x, layer, mesh=None):
    """One transformer block. ``layer`` = this layer's params (leading L dim
    already indexed away by scan)."""
    from jax.sharding import PartitionSpec as P

    def c(y, spec):
        if mesh is None:
            return y
        from ray_tpu.parallel.sharding import constrain

        return constrain(y, mesh, spec)

    dt = x.dtype
    b, s, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim

    ln1 = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
    qkv = ln1 @ layer["attn_qkv"]["kernel"].astype(dt) + layer["attn_qkv"]["bias"].astype(dt)
    # seq stays sharded over sp end-to-end (sequence parallelism); sp=1
    # meshes make these the same constraints as before.
    qkv = c(qkv, P(("dp", "fsdp"), "sp", "tp"))
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, h, hd).transpose(0, 2, 1, 3)

    impl = cfg.attn_impl
    if impl == "ring" and mesh is None:
        raise ValueError(
            "attn_impl='ring' needs a device mesh with an 'sp' axis; pass "
            "mesh= (or use attn_impl='auto', which picks ring only when the "
            "mesh shards sequence)"
        )
    if impl == "ring" or (
        impl == "auto" and mesh is not None and mesh.shape.get("sp", 1) > 1
    ):
        # sequence sharded over sp: ring attention rotates KV over ICI
        from ray_tpu.ops.ring_attention import ring_attention_sharded

        att = ring_attention_sharded(heads(q), heads(k), heads(v), mesh)
    else:
        from ray_tpu.ops.flash_attention import _interpret, flash_shardable

        want_flash = impl == "flash" or (impl == "auto" and not _interpret())
        if (
            want_flash
            and mesh is not None
            and mesh.size > 1
            and s >= 128
            and s % 128 == 0
            and flash_shardable(b, h, mesh)
        ):
            # multi-device pjit: shard_map the Pallas kernel so it runs on
            # each chip's dp/tp shard instead of being replicated (no GSPMD
            # rule for a bare pallas_call)
            from ray_tpu.ops.flash_attention import flash_attention_sharded

            att = flash_attention_sharded(heads(q), heads(k), heads(v), mesh)
        elif want_flash and mesh is not None and mesh.size > 1:
            # multi-device but not shardable (batch/heads don't divide the
            # mesh): a bare pallas_call would replicate on every chip — the
            # XLA einsum partitions correctly instead
            att = causal_attention(heads(q), heads(k), heads(v), impl="xla")
        else:
            att = causal_attention(heads(q), heads(k), heads(v), impl=impl)
    att = att.transpose(0, 2, 1, 3).reshape(b, s, d)
    att = att @ layer["attn_out"]["kernel"].astype(dt) + layer["attn_out"]["bias"].astype(dt)
    x = x + c(att, P(("dp", "fsdp"), "sp", None))

    ln2 = _layernorm(x, layer["ln2"]["scale"], layer["ln2"]["bias"])
    hmid = jax.nn.gelu(ln2 @ layer["mlp_in"]["kernel"].astype(dt) + layer["mlp_in"]["bias"].astype(dt))
    hmid = c(hmid, P(("dp", "fsdp"), "sp", "tp"))
    out = hmid @ layer["mlp_out"]["kernel"].astype(dt) + layer["mlp_out"]["bias"].astype(dt)
    return x + c(out, P(("dp", "fsdp"), "sp", None))


def gpt_forward(cfg: GPTConfig, params: dict, tokens: jax.Array, mesh=None) -> jax.Array:
    """tokens (batch, seq) int32 → logits (batch, seq, vocab) fp32."""
    dt = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    x = params["embed"]["tokens"].astype(dt)[tokens]
    x = x + params["embed"]["pos"].astype(dt)[:s]

    block = lambda carry, layer: (_block(cfg, carry, layer, mesh), None)
    if cfg.remat:
        block = jax.checkpoint(block, prevent_cse=False)
    x, _ = jax.lax.scan(block, x, params["blocks"])

    x = _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    logits = x.astype(jnp.float32) @ params["lm_head"]["kernel"]
    return logits


def gpt_loss(cfg: GPTConfig, params: dict, tokens: jax.Array, mesh=None) -> jax.Array:
    """Next-token cross-entropy, mean over (batch, seq-1)."""
    logits = gpt_forward(cfg, params, tokens[:, :-1], mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -ll.mean()
