"""GPT-J family decoder — the reference's north-star model, TPU-first.

The reference's headline benchmark fine-tunes GPT-J-6B with DeepSpeed
ZeRO-3 (``release/air_examples/gptj_deepspeed_finetuning/
gptj_deepspeed_fine_tuning.ipynb``). This module implements the GPT-J
architecture natively on the JAX/XLA stack so real HF checkpoints run on
TPU (import: ``train/integrations/huggingface.load_hf_gptj``):

* rotary position embeddings on the first ``rotary_dim`` dims of every
  head, GPT-J's INTERLEAVED (rotate-every-two) variant — no learned
  positional table;
* parallel residual: ``x + attn(ln(x)) + mlp(ln(x))`` with a single
  layernorm per block (not GPT-2's sequential two-LN form);
* no biases on q/k/v/out projections; untied lm head WITH bias;
* same TPU shape discipline as ``models.gpt``: stacked-layer pytree +
  ``lax.scan`` + per-block remat, bf16 compute / fp32 master params,
  Pallas flash attention, blockwise fused CE for training;
* greedy KV-cache decode (static shapes: cache is (L, b, h, max, hd),
  ``lax.fori_loop`` over new tokens) for inference benchmarks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import causal_attention


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    seq_len: int = 2048
    d_model: int = 4096
    n_layers: int = 28
    n_heads: int = 16
    rotary_dim: int = 64
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "full"
    attn_impl: str = "auto"
    fused_loss: bool = True
    ce_chunks: Optional[int] = None

    @property
    def d_ff(self) -> int:
        return 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# GPT-J-6B checkpoint shape (vocab padded to 50432 stays MXU-aligned when
# requested at import time; HF ships 50400)
GPTJ_6B = GPTJConfig()


def gptj_init(rng: jax.Array, cfg: GPTJConfig) -> dict:
    """Random-init parameter pytree (fp32 master), HF-shape-compatible."""
    d, dff, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size
    ks = jax.random.split(rng, 8)

    def kernel(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) * (fan_in**-0.5)

    blocks = {
        "ln1": {"scale": jnp.ones((L, d)), "bias": jnp.zeros((L, d))},
        "q": {"kernel": kernel(ks[0], (L, d, d), d)},
        "k": {"kernel": kernel(ks[1], (L, d, d), d)},
        "v": {"kernel": kernel(ks[2], (L, d, d), d)},
        "attn_out": {"kernel": kernel(ks[3], (L, d, d), d)},
        "mlp_in": {"kernel": kernel(ks[4], (L, d, dff), d), "bias": jnp.zeros((L, dff))},
        "mlp_out": {"kernel": kernel(ks[5], (L, dff, d), dff), "bias": jnp.zeros((L, d))},
    }
    return {
        "embed": {"tokens": jax.nn.initializers.normal(0.02)(ks[6], (V, d), jnp.float32)},
        "blocks": blocks,
        "ln_f": {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))},
        "lm_head": {"kernel": kernel(ks[7], (d, V), d), "bias": jnp.zeros((V,))},
    }


def _layernorm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias
    return out.astype(x.dtype)


def _rotary_sincos(positions: jax.Array, rotary_dim: int):
    """GPT-J sinusoid table for given positions: (n, rotary_dim/2) each."""
    inv_freq = 1.0 / (
        10000.0 ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.sin(ang), jnp.cos(ang)


def _apply_rotary(x: jax.Array, sin: jax.Array, cos: jax.Array, rotary_dim: int):
    """Interleaved (rotate-every-two) rotary on the first ``rotary_dim``
    dims. x: (b, h, s, hd); sin/cos: (s, rotary_dim/2). Matches HF GPT-J's
    ``rotate_every_two`` + ``duplicate_interleave`` exactly (fp32 math)."""
    rot, pas = x[..., :rotary_dim], x[..., rotary_dim:]
    r = rot.astype(jnp.float32).reshape(*rot.shape[:-1], rotary_dim // 2, 2)
    x1, x2 = r[..., 0], r[..., 1]
    s = sin[None, None, :, :]
    c = cos[None, None, :, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1).reshape(rot.shape).astype(x.dtype)
    return jnp.concatenate([out, pas], axis=-1) if pas.shape[-1] else out


def _project_qkv(cfg: GPTJConfig, h, layer, positions):
    """(q, k, v) heads with rotary applied: (b, heads, s, hd) each."""
    dt = h.dtype
    b, s, d = h.shape
    nh, hd = cfg.n_heads, cfg.head_dim

    def heads(t):
        return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

    q = heads(h @ layer["q"]["kernel"].astype(dt))
    k = heads(h @ layer["k"]["kernel"].astype(dt))
    v = heads(h @ layer["v"]["kernel"].astype(dt))
    sin, cos = _rotary_sincos(positions, cfg.rotary_dim)
    q = _apply_rotary(q, sin, cos, cfg.rotary_dim)
    k = _apply_rotary(k, sin, cos, cfg.rotary_dim)
    return q, k, v


def _block(cfg: GPTJConfig, x, layer, positions, mesh=None):
    """One GPT-J block: parallel attention + MLP over one layernorm.
    ``mesh`` places the same activation sharding constraints models.gpt
    uses (batch over dp/fsdp, hidden over tp) so pjit keeps activations
    scattered under ZeRO/TP instead of replicating them."""
    from jax.sharding import PartitionSpec as P

    def c(y, spec):
        if mesh is None:
            return y
        from ray_tpu.parallel.sharding import constrain

        return constrain(y, mesh, spec)

    dt = x.dtype
    b, s, d = x.shape
    h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
    h = c(h, P(("dp", "fsdp"), None, None))
    q, k, v = _project_qkv(cfg, h, layer, positions)
    att = causal_attention(q, k, v, impl=cfg.attn_impl)
    att = att.transpose(0, 2, 1, 3).reshape(b, s, d)
    att = att @ layer["attn_out"]["kernel"].astype(dt)
    att = c(att, P(("dp", "fsdp"), None, None))
    mid = jax.nn.gelu(
        h @ layer["mlp_in"]["kernel"].astype(dt) + layer["mlp_in"]["bias"].astype(dt)
    )
    mid = c(mid, P(("dp", "fsdp"), None, "tp"))
    mlp = mid @ layer["mlp_out"]["kernel"].astype(dt) + layer["mlp_out"]["bias"].astype(dt)
    return x + att + c(mlp, P(("dp", "fsdp"), None, None))


_REMAT_POLICIES = {
    "full": lambda: None,
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    "attn": lambda: jax.checkpoint_policies.save_only_these_names(
        "flash_out", "flash_lse"
    ),
}


def gptj_hidden(cfg: GPTJConfig, params: dict, tokens: jax.Array, mesh=None):
    """tokens (b, s) int32 → final hidden (b, s, d) in activation dtype."""
    dt = jnp.dtype(cfg.dtype)
    b, s = tokens.shape
    x = params["embed"]["tokens"][tokens].astype(dt)
    positions = jnp.arange(s)

    def block(carry, layer):
        return _block(cfg, carry, layer, positions, mesh), None

    if cfg.remat:
        policy = _REMAT_POLICIES[cfg.remat_policy]()
        block = jax.checkpoint(block, prevent_cse=False, policy=policy)
    x, _ = jax.lax.scan(block, x, params["blocks"])
    return _layernorm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])


def gptj_forward(
    cfg: GPTJConfig, params: dict, tokens: jax.Array, mesh=None
) -> jax.Array:
    """logits (b, s, vocab) fp32."""
    x = gptj_hidden(cfg, params, tokens, mesh)
    return (
        x.astype(jnp.float32) @ params["lm_head"]["kernel"]
        + params["lm_head"]["bias"]
    )


def gptj_loss(
    cfg: GPTJConfig, params: dict, tokens: jax.Array, mesh=None
) -> jax.Array:
    """Next-token cross-entropy (mean); fused blockwise CE by default."""
    hidden = gptj_hidden(cfg, params, tokens[:, :-1], mesh)
    targets = tokens[:, 1:]
    if cfg.fused_loss:
        from ray_tpu.ops.fused_ce import fused_softmax_cross_entropy_bias

        b, s, d = hidden.shape
        losses = fused_softmax_cross_entropy_bias(
            hidden.reshape(b * s, d),
            params["lm_head"]["kernel"],
            params["lm_head"]["bias"],
            targets.reshape(-1).astype(jnp.int32),
            cfg.ce_chunks,
        )
        return losses.mean()
    logits = (
        hidden.astype(jnp.float32) @ params["lm_head"]["kernel"]
        + params["lm_head"]["bias"]
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0].mean()


# ---------------------------------------------------------------------------
# greedy KV-cache decode (inference benchmark path)
# ---------------------------------------------------------------------------


def _attend_cached(q1, k_cache, v_cache, length):
    """Single-position attention against a cache. q1: (b, h, hd);
    k/v_cache: (b, h, max, hd); ``length`` = valid prefix (the new token's
    k/v already written). Plain einsum — one query row needs no kernel."""
    scale = q1.shape[-1] ** -0.5
    logits = jnp.einsum("bhd,bhsd->bhs", q1.astype(jnp.float32), k_cache.astype(jnp.float32))
    logits = logits * scale
    mask = jnp.arange(k_cache.shape[2])[None, None, :] < length
    logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhs,bhsd->bhd", probs, v_cache.astype(jnp.float32))


def gptj_decode(
    cfg: GPTJConfig,
    params: dict,
    prompt: jax.Array,
    n_new: int,
    *,
    key: Optional[jax.Array] = None,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """Decode ``n_new`` tokens after ``prompt`` (b, s0) int32 →
    (b, s0 + n_new). Prefill computes the prompt's KV cache in one forward;
    each new token is a single-position pass over the cache (static shapes
    throughout: jit once, decode under ``lax.fori_loop``).

    Sampling: greedy by default (``key=None``). With a PRNG ``key``,
    per-token temperature / top-k / top-p sampling via
    ``models.sampling.sample_tokens`` (scalars or per-row arrays); step
    ``i`` folds ``i`` into the key, so continuation from any prefix is
    reproducible."""
    dt = jnp.dtype(cfg.dtype)
    b, s0 = prompt.shape
    L, nh, hd = cfg.n_layers, cfg.n_heads, cfg.head_dim
    max_len = s0 + n_new

    # ---- prefill: run the normal stacked forward, capturing per-layer k/v
    x = params["embed"]["tokens"][prompt].astype(dt)
    positions = jnp.arange(s0)

    def prefill_block(carry, layer):
        h = _layernorm(carry, layer["ln1"]["scale"], layer["ln1"]["bias"])
        q, k, v = _project_qkv(cfg, h, layer, positions)
        att = causal_attention(q, k, v, impl="xla")  # s0 may be ragged
        att = att.transpose(0, 2, 1, 3).reshape(b, s0, cfg.d_model)
        att = att @ layer["attn_out"]["kernel"].astype(dt)
        mid = jax.nn.gelu(
            h @ layer["mlp_in"]["kernel"].astype(dt)
            + layer["mlp_in"]["bias"].astype(dt)
        )
        mlp = (
            mid @ layer["mlp_out"]["kernel"].astype(dt)
            + layer["mlp_out"]["bias"].astype(dt)
        )
        pad = jnp.zeros((b, nh, n_new, hd), dt)
        kc = jnp.concatenate([k.astype(dt), pad], axis=2)
        vc = jnp.concatenate([v.astype(dt), pad], axis=2)
        return carry + att + mlp, (kc, vc)

    def pick(logits, step_idx):
        if key is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        from ray_tpu.models.sampling import sample_tokens

        return sample_tokens(
            logits, jax.random.fold_in(key, step_idx), temperature, top_k, top_p
        )

    x, (k_caches, v_caches) = jax.lax.scan(prefill_block, x, params["blocks"])
    hlast = _layernorm(
        x[:, -1], params["ln_f"]["scale"], params["ln_f"]["bias"]
    )
    logits = hlast.astype(jnp.float32) @ params["lm_head"]["kernel"] + params["lm_head"]["bias"]
    first_new = pick(logits, 0)  # (b,)

    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, n_new), jnp.int32)], axis=1
    )
    tokens = jax.lax.dynamic_update_slice(tokens, first_new[:, None], (0, s0))

    def step(i, carry):
        tokens, k_caches, v_caches = carry
        pos = s0 + i  # position of the token being FED
        tok = jax.lax.dynamic_slice(tokens, (0, pos), (b, 1))[:, 0]
        x1 = params["embed"]["tokens"][tok].astype(dt)  # (b, d)

        def one_layer(carry1, inputs):
            x1 = carry1
            layer, kc, vc = inputs
            h1 = _layernorm(
                x1[:, None, :], layer["ln1"]["scale"], layer["ln1"]["bias"]
            )
            q, k, v = _project_qkv(cfg, h1, layer, jnp.expand_dims(pos, 0))
            kc = jax.lax.dynamic_update_slice(kc, k.astype(dt), (0, 0, pos, 0))
            vc = jax.lax.dynamic_update_slice(vc, v.astype(dt), (0, 0, pos, 0))
            # (b, h, hd) merges h-major straight back to (b, d)
            att = _attend_cached(q[:, :, 0], kc, vc, pos + 1).astype(dt)
            att = att.reshape(b, cfg.d_model) @ layer["attn_out"]["kernel"].astype(dt)
            h1f = h1[:, 0]
            mid = jax.nn.gelu(
                h1f @ layer["mlp_in"]["kernel"].astype(dt)
                + layer["mlp_in"]["bias"].astype(dt)
            )
            mlp = (
                mid @ layer["mlp_out"]["kernel"].astype(dt)
                + layer["mlp_out"]["bias"].astype(dt)
            )
            return x1 + att + mlp, (kc, vc)

        x1, (k_caches, v_caches) = jax.lax.scan(
            one_layer, x1, (params["blocks"], k_caches, v_caches)
        )
        h1 = _layernorm(x1, params["ln_f"]["scale"], params["ln_f"]["bias"])
        logits = (
            h1.astype(jnp.float32) @ params["lm_head"]["kernel"]
            + params["lm_head"]["bias"]
        )
        nxt = pick(logits, i + 1)
        tokens = jax.lax.dynamic_update_slice(tokens, nxt[:, None], (0, pos + 1))
        return tokens, k_caches, v_caches

    tokens, _, _ = jax.lax.fori_loop(
        0, n_new - 1, step, (tokens, k_caches, v_caches)
    )
    return tokens
