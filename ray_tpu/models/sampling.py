"""Per-token sampling shared by the decode paths and the LLM engine.

Two helpers:

* ``sample_tokens`` — greedy / temperature / top-k / top-p over a batch
  of next-token logit rows, with every knob accepted either as a scalar
  (whole batch) or as a per-row array (the continuous-batching engine
  mixes requests with different sampling params in one decode step).
* ``speculative_verify`` — the accept/reject half of speculative
  decoding for ONE sequence's ``w = k+1``-position verification window,
  by SAMPLE-THEN-MATCH: position ``i`` draws the target token ``t_i``
  from the SAME filtered distribution (and the same per-index PRNG key)
  ``sample_tokens`` would have used at that output index, then accepts
  the drafted prefix while ``draft_i == t_i`` and always emits ``t_i``.
  Both built-in drafters are deterministic (point-mass proposals), so
  this has exactly the acceptance probability of textbook rejection
  sampling — accept ``x_i`` with ``p_i(x_i)``, i.e. ``min(1, p/q)`` with
  ``q`` a point mass — while being stronger than the delta/residual
  formulation where it matters: the emitted token at output index ``i``
  depends only on ``(seed, i, prefix)``, never on where the verification
  window happened to start, so sampled speculative decode is per-seed
  reproducible across runs AND token-identical to the non-speculative
  sampled path (greedy falls out as the ``temperature <= 0`` argmax
  special case).

Everything is jit-safe with static shapes: dynamic per-row ``k`` is
implemented by ranking a full descending sort rather than ``lax.top_k``
(whose k must be static), which also gives top-p its cumulative mass for
free from the same sort.

Convention: ``temperature <= 0`` means greedy (argmax) for that row —
the PRNG key is still consumed uniformly so a batch mixing greedy and
sampled rows stays deterministic per-row regardless of its neighbors.

The (seed, absolute output index) keying is ALSO the serve plane's
mid-stream-failover guarantee (RESILIENCE.md): a replica that dies
mid-stream is replaced by re-submitting prompt + delivered tokens
(``LLMEngine.submit(resume_tokens=...)``), and because the token at
output index ``i`` depends only on ``(seed, i, prefix)`` — never on
which replica, verification window, or failover attempt produced it —
the resumed stream is token-identical to the unkilled run, under greedy
and seeded sampling alike. Any future sampling change MUST preserve
this: key by absolute output position, not by step/window/attempt.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _filtered_logits(logits, temp, kk, pp):
    """Temperature-scaled logits with top-k/top-p support masked to
    ``_NEG_INF``.  logits: (b, v) fp32; temp/kk/pp: (b,) arrays.  This IS
    the distribution ``sample_tokens`` draws from — ``speculative_verify``
    must score draft tokens under exactly the same filtering or the
    accepted distribution would drift from the non-speculative path."""
    b, v = logits.shape
    safe_t = jnp.maximum(temp, 1e-6)[:, None]
    scaled = logits / safe_t
    # one descending sort serves both truncations: rank < k for top-k,
    # exclusive cumulative mass < p for top-p (rank 0 always survives)
    order = jnp.argsort(-scaled, axis=-1)
    sorted_scaled = jnp.take_along_axis(scaled, order, axis=-1)
    ranks = jnp.arange(v)[None, :]
    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (kk[:, None] <= 0) | (ranks < kk[:, None])
    keep &= (cum - probs) < pp[:, None]
    masked_sorted = jnp.where(keep, sorted_scaled, _NEG_INF)
    # scatter the surviving logits back to vocab order
    return (
        jnp.full_like(scaled, _NEG_INF)
        .at[jnp.arange(b)[:, None], order]
        .set(masked_sorted)
    )


def sample_tokens(
    logits: jax.Array,
    key: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """Sample one token id per row: (batch, vocab) fp logits -> (batch,) int32.

    ``temperature``/``top_k``/``top_p`` are scalars or (batch,) arrays.
    ``top_k <= 0`` disables the k-truncation; ``top_p >= 1`` the nucleus
    truncation; ``temperature <= 0`` selects greedy argmax for that row.
    ``key`` is one PRNG key for the whole call — rows draw from
    per-row splits so the same (key, row) pair always reproduces.
    """
    logits = logits.astype(jnp.float32)
    b, v = logits.shape
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    kk = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    pp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked = _filtered_logits(logits, temp, kk, pp)
    keys = jax.random.split(key, b)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def speculative_verify(
    logits: jax.Array,
    draft: jax.Array,
    seed: jax.Array,
    counter: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
):
    """Accept/reject one sequence's drafted window against target logits.

    ``logits``: (w, vocab) — the target model's logits at the window's w
    positions (position i conditioned on the draft tokens before it);
    ``draft``: (w-1,) int32 — the drafter's proposals; ``seed``/``counter``
    — the request's sampling seed and the output index of the window's
    FIRST token.

    Sample-then-match (module doc): window index i draws ``out[i]`` with
    the PRNG key ``fold_in(PRNGKey(seed), counter + i)`` — the exact key
    AND filtered distribution the plain decode path's per-row sampler
    uses at that output index — then the drafted prefix is accepted while
    ``draft[i] == out[i]``.  ``out[i]`` is only CONDITIONALLY valid: its
    logits assumed the draft prefix before it, which holds exactly up
    through the first mismatch, so callers emit ``out[:n_accepted + 1]``
    (accepted prefix + one correction/bonus token — the first mismatch's
    replacement, or the bonus position when everything matched) and
    ignore the rest.

    Greedy (``temperature <= 0``) accepts while ``draft[i] == argmax`` —
    the emitted chain is exactly the sequential argmax chain.  Either
    way the emitted token at output index i depends only on
    (seed, i, prefix): identical to non-speculative decode, whatever the
    drafter proposed and wherever the window boundaries fell.
    """
    logits = logits.astype(jnp.float32)
    w, v = logits.shape
    kd = w - 1
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (w,))
    kk = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (w,))
    pp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (w,))

    base = jax.random.PRNGKey(seed)
    keys = jax.vmap(
        lambda i: jax.random.fold_in(base, counter + i)
    )(jnp.arange(w, dtype=jnp.int32))  # (w, 2)
    out = jax.vmap(
        lambda lg, key, t, k_, p_: sample_tokens(
            lg[None, :], key, t[None], k_[None], p_[None]
        )[0]
    )(logits, keys, temp, kk, pp)  # (w,) int32

    if kd:
        accept = draft == out[:kd]
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32))).astype(jnp.int32)
    else:  # empty draft (w == 1): the window is just the bonus position
        n_acc = jnp.int32(0)
    return n_acc, out
