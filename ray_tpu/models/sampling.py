"""Per-token sampling shared by the decode paths and the LLM engine.

One helper — ``sample_tokens`` — implements greedy / temperature /
top-k / top-p over a batch of next-token logit rows, with every knob
accepted either as a scalar (whole batch) or as a per-row array (the
continuous-batching engine mixes requests with different sampling params
in one decode step). Everything is jit-safe with static shapes: dynamic
per-row ``k`` is implemented by ranking a full descending sort rather
than ``lax.top_k`` (whose k must be static), which also gives top-p its
cumulative mass for free from the same sort.

Convention: ``temperature <= 0`` means greedy (argmax) for that row —
the PRNG key is still consumed uniformly so a batch mixing greedy and
sampled rows stays deterministic per-row regardless of its neighbors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def sample_tokens(
    logits: jax.Array,
    key: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """Sample one token id per row: (batch, vocab) fp logits -> (batch,) int32.

    ``temperature``/``top_k``/``top_p`` are scalars or (batch,) arrays.
    ``top_k <= 0`` disables the k-truncation; ``top_p >= 1`` the nucleus
    truncation; ``temperature <= 0`` selects greedy argmax for that row.
    ``key`` is one PRNG key for the whole call — rows draw from
    per-row splits so the same (key, row) pair always reproduces.
    """
    logits = logits.astype(jnp.float32)
    b, v = logits.shape
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    kk = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    pp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_t = jnp.maximum(temp, 1e-6)[:, None]
    scaled = logits / safe_t
    # one descending sort serves both truncations: rank < k for top-k,
    # exclusive cumulative mass < p for top-p (rank 0 always survives)
    order = jnp.argsort(-scaled, axis=-1)
    sorted_scaled = jnp.take_along_axis(scaled, order, axis=-1)
    ranks = jnp.arange(v)[None, :]
    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (kk[:, None] <= 0) | (ranks < kk[:, None])
    keep &= (cum - probs) < pp[:, None]
    masked_sorted = jnp.where(keep, sorted_scaled, _NEG_INF)
    # scatter the surviving logits back to vocab order
    masked = (
        jnp.full_like(scaled, _NEG_INF)
        .at[jnp.arange(b)[:, None], order]
        .set(masked_sorted)
    )
    keys = jax.random.split(key, b)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)
