"""Per-token sampling shared by the decode paths and the LLM engine.

Two helpers:

* ``sample_tokens`` — greedy / temperature / top-k / top-p over a batch
  of next-token logit rows, with every knob accepted either as a scalar
  (whole batch) or as a per-row array (the continuous-batching engine
  mixes requests with different sampling params in one decode step).
* ``speculative_verify`` — the accept/reject half of speculative
  decoding for ONE sequence's ``w = k+1``-position verification window,
  by SAMPLE-THEN-MATCH: position ``i`` draws the target token ``t_i``
  from the SAME filtered distribution (and the same per-index PRNG key)
  ``sample_tokens`` would have used at that output index, then accepts
  the drafted prefix while ``draft_i == t_i`` and always emits ``t_i``.
  Both built-in drafters are deterministic (point-mass proposals), so
  this has exactly the acceptance probability of textbook rejection
  sampling — accept ``x_i`` with ``p_i(x_i)``, i.e. ``min(1, p/q)`` with
  ``q`` a point mass — while being stronger than the delta/residual
  formulation where it matters: the emitted token at output index ``i``
  depends only on ``(seed, i, prefix)``, never on where the verification
  window happened to start, so sampled speculative decode is per-seed
  reproducible across runs AND token-identical to the non-speculative
  sampled path (greedy falls out as the ``temperature <= 0`` argmax
  special case).

Everything is jit-safe with static shapes: dynamic per-row ``k`` is
implemented by ranking a full descending sort rather than ``lax.top_k``
(whose k must be static), which also gives top-p its cumulative mass for
free from the same sort.

Convention: ``temperature <= 0`` means greedy (argmax) for that row —
the PRNG key is still consumed uniformly so a batch mixing greedy and
sampled rows stays deterministic per-row regardless of its neighbors.

The (seed, absolute output index) keying is ALSO the serve plane's
mid-stream-failover guarantee (RESILIENCE.md): a replica that dies
mid-stream is replaced by re-submitting prompt + delivered tokens
(``LLMEngine.submit(resume_tokens=...)``), and because the token at
output index ``i`` depends only on ``(seed, i, prefix)`` — never on
which replica, verification window, or failover attempt produced it —
the resumed stream is token-identical to the unkilled run, under greedy
and seeded sampling alike. Any future sampling change MUST preserve
this: key by absolute output position, not by step/window/attempt.

Logprob capture (the ``ray_tpu.rlhf`` behavior-policy contract): every
sampling entry point has a ``*_logprobs`` variant that also returns the
log-probability of the CHOSEN token under the exact distribution it was
drawn from — ``log_softmax`` of the temperature-scaled, top-k/top-p
masked logits for sampled rows, ``log_softmax`` of the raw logits at the
argmax for greedy rows (a point mass has no useful density; the raw
model confidence is the informative number and is what a scorer
recomputing ``log_softmax`` at the greedy id gets). ``token_logprobs``
is the matching SCORING entry point: given token ids instead of a PRNG
key it returns the same quantity, so an RLHF learner can evaluate its
current policy on rollout tokens in exactly the units the engine
captured behavior logprobs in — the importance ratio
``exp(current - behavior)`` is then exact, whatever sampling knobs the
rollout used. Since both are pure functions of (logits, knobs, id), the
captured value at output index ``i`` inherits the failover contract
above: identical across spec-decode window alignments, resumes, and
replicas.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _filtered_logits(logits, temp, kk, pp):
    """Temperature-scaled logits with top-k/top-p support masked to
    ``_NEG_INF``.  logits: (b, v) fp32; temp/kk/pp: (b,) arrays.  This IS
    the distribution ``sample_tokens`` draws from — ``speculative_verify``
    must score draft tokens under exactly the same filtering or the
    accepted distribution would drift from the non-speculative path."""
    b, v = logits.shape
    safe_t = jnp.maximum(temp, 1e-6)[:, None]
    scaled = logits / safe_t
    # one descending sort serves both truncations: rank < k for top-k,
    # exclusive cumulative mass < p for top-p (rank 0 always survives)
    order = jnp.argsort(-scaled, axis=-1)
    sorted_scaled = jnp.take_along_axis(scaled, order, axis=-1)
    ranks = jnp.arange(v)[None, :]
    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (kk[:, None] <= 0) | (ranks < kk[:, None])
    keep &= (cum - probs) < pp[:, None]
    masked_sorted = jnp.where(keep, sorted_scaled, _NEG_INF)
    # scatter the surviving logits back to vocab order
    return (
        jnp.full_like(scaled, _NEG_INF)
        .at[jnp.arange(b)[:, None], order]
        .set(masked_sorted)
    )


def _broadcast_knobs(b, temperature, top_k, top_p):
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    kk = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    pp = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    return temp, kk, pp


def _chosen_logprob(logits, masked, temp, tok):
    """Module-doc logprob convention: sampled rows score under the
    filtered distribution they drew from, greedy rows under the raw
    logits (log_softmax at the argmax id)."""
    idx = tok[:, None]
    lp_sampled = jnp.take_along_axis(
        jax.nn.log_softmax(masked, axis=-1), idx, axis=-1
    )[:, 0]
    lp_greedy = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), idx, axis=-1
    )[:, 0]
    return jnp.where(temp > 0.0, lp_sampled, lp_greedy)


def sample_tokens_logprobs(
    logits: jax.Array,
    key: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> tuple[jax.Array, jax.Array]:
    """``sample_tokens`` that also returns each chosen token's logprob
    ((batch,) float32) under the module-doc convention — the behavior
    logprob the RLHF importance ratio needs, captured at zero extra
    model cost (the softmax already exists on device)."""
    logits = logits.astype(jnp.float32)
    b, v = logits.shape
    temp, kk, pp = _broadcast_knobs(b, temperature, top_k, top_p)

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked = _filtered_logits(logits, temp, kk, pp)
    keys = jax.random.split(key, b)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    tok = jnp.where(temp > 0.0, sampled, greedy)
    return tok, _chosen_logprob(logits, masked, temp, tok)


def sample_tokens(
    logits: jax.Array,
    key: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """Sample one token id per row: (batch, vocab) fp logits -> (batch,) int32.

    ``temperature``/``top_k``/``top_p`` are scalars or (batch,) arrays.
    ``top_k <= 0`` disables the k-truncation; ``top_p >= 1`` the nucleus
    truncation; ``temperature <= 0`` selects greedy argmax for that row.
    ``key`` is one PRNG key for the whole call — rows draw from
    per-row splits so the same (key, row) pair always reproduces.
    """
    return sample_tokens_logprobs(logits, key, temperature, top_k, top_p)[0]


def token_logprobs(
    logits: jax.Array,
    tokens: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """Score GIVEN token ids under the exact sampling distribution:
    (batch, vocab) logits + (batch,) int32 ids -> (batch,) float32
    logprobs, same convention as ``sample_tokens_logprobs`` (module doc).

    This is the learner-side half of the RLHF importance ratio: the
    engine captures behavior logprobs with ``sample_tokens_logprobs``;
    the learner recomputes current-policy logprobs of the same tokens
    with THIS function and the same knobs, so ``exp(cur - behavior)`` is
    an exact density ratio. Differentiable w.r.t. ``logits`` (the
    top-k/top-p mask is treated as constant, standard straight-through
    practice for truncated-sampling objectives).

    A token the filter masked out scores ``-inf``-like (≈ -1e30 shifted
    by the log-normalizer): it had probability 0 under the behavior
    distribution, which is exactly what the ratio math wants.
    """
    logits = logits.astype(jnp.float32)
    b, v = logits.shape
    temp, kk, pp = _broadcast_knobs(b, temperature, top_k, top_p)
    masked = _filtered_logits(logits, temp, kk, pp)
    return _chosen_logprob(logits, masked, temp, tokens.astype(jnp.int32))


def speculative_verify_logprobs(
    logits: jax.Array,
    draft: jax.Array,
    seed: jax.Array,
    counter: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
):
    """``speculative_verify`` that also returns (w,) logprobs of the
    emitted tokens — window index ``i``'s entry scores ``out[i]`` under
    the exact per-index filtered distribution (same convention as
    ``sample_tokens_logprobs``), so spec-decode rollouts capture behavior
    logprobs identical to the plain decode path's (verification already
    computes every per-index distribution; reading the chosen density is
    free). Validity mirrors ``out``: entries past ``n_accepted`` are
    conditioned on a rejected prefix and must be discarded with their
    tokens."""
    logits = logits.astype(jnp.float32)
    w, v = logits.shape
    kd = w - 1
    temp, kk, pp = _broadcast_knobs(w, temperature, top_k, top_p)

    base = jax.random.PRNGKey(seed)
    keys = jax.vmap(
        lambda i: jax.random.fold_in(base, counter + i)
    )(jnp.arange(w, dtype=jnp.int32))  # (w, 2)

    def one(lg, key, t, k_, p_):
        tok, lp = sample_tokens_logprobs(
            lg[None, :], key, t[None], k_[None], p_[None]
        )
        return tok[0], lp[0]

    out, logp = jax.vmap(one)(logits, keys, temp, kk, pp)  # (w,), (w,)

    if kd:
        accept = draft == out[:kd]
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32))).astype(jnp.int32)
    else:  # empty draft (w == 1): the window is just the bonus position
        n_acc = jnp.int32(0)
    return n_acc, out, logp


def speculative_verify(
    logits: jax.Array,
    draft: jax.Array,
    seed: jax.Array,
    counter: jax.Array,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
):
    """Accept/reject one sequence's drafted window against target logits.

    ``logits``: (w, vocab) — the target model's logits at the window's w
    positions (position i conditioned on the draft tokens before it);
    ``draft``: (w-1,) int32 — the drafter's proposals; ``seed``/``counter``
    — the request's sampling seed and the output index of the window's
    FIRST token.

    Sample-then-match (module doc): window index i draws ``out[i]`` with
    the PRNG key ``fold_in(PRNGKey(seed), counter + i)`` — the exact key
    AND filtered distribution the plain decode path's per-row sampler
    uses at that output index — then the drafted prefix is accepted while
    ``draft[i] == out[i]``.  ``out[i]`` is only CONDITIONALLY valid: its
    logits assumed the draft prefix before it, which holds exactly up
    through the first mismatch, so callers emit ``out[:n_accepted + 1]``
    (accepted prefix + one correction/bonus token — the first mismatch's
    replacement, or the bonus position when everything matched) and
    ignore the rest.

    Greedy (``temperature <= 0``) accepts while ``draft[i] == argmax`` —
    the emitted chain is exactly the sequential argmax chain.  Either
    way the emitted token at output index i depends only on
    (seed, i, prefix): identical to non-speculative decode, whatever the
    drafter proposed and wherever the window boundaries fell.
    """
    n_acc, out, _ = speculative_verify_logprobs(
        logits, draft, seed, counter, temperature, top_k, top_p
    )
    return n_acc, out
