"""Model zoo: TPU-first architectures as pure-JAX parameter pytrees.

The flagship is ``gpt`` (decoder-only transformer, the shape of the
reference's GPT-J-6B north-star fine-tune). Models here are functions, not
modules: ``init(rng, cfg) -> params`` and ``forward(cfg, params, tokens)``,
stacked over layers for ``lax.scan`` (fast compiles at depth) and annotated
for the sharding rule table in ``ray_tpu.parallel.sharding``.
"""

from ray_tpu.models.gpt import GPTConfig, gpt_forward, gpt_init, gpt_loss  # noqa: F401
