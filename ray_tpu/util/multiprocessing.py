"""``multiprocessing.Pool``-compatible API over cluster tasks.

Counterpart of the reference's ``ray.util.multiprocessing`` shim: the
stdlib Pool surface (apply/map/imap/starmap + async variants) where each
work item is a task, so a Pool transparently spans every host in the
cluster instead of one machine's fork pool.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Optional

import ray_tpu


class AsyncResult:
    """Matches ``multiprocessing.pool.AsyncResult``."""

    def __init__(self, refs, single: bool, callback=None, error_callback=None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._done = False
        self._value = None
        self._error: Optional[BaseException] = None

    def _resolve(self, timeout=None):
        if self._done:
            return
        try:
            vals = ray_tpu.get(self._refs, timeout=timeout)
            self._value = vals[0] if self._single else vals
            if self._callback is not None:
                self._callback(self._value)
        except ray_tpu.exceptions.GetTimeoutError:
            # stdlib semantics: a timed-out get raises TimeoutError but does
            # NOT consume the result — a later get() can still succeed
            import multiprocessing

            raise multiprocessing.TimeoutError()
        except BaseException as e:  # noqa: BLE001 - stdlib Pool semantics
            self._error = e
            if self._error_callback is not None:
                self._error_callback(e)
        self._done = True

    def get(self, timeout=None):
        self._resolve(timeout)
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout=None):
        ray_tpu.wait(list(self._refs), num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(list(self._refs), num_returns=len(self._refs), timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self._done:
            raise ValueError("result is not ready")
        return self._error is None


class Pool:
    """Drop-in ``multiprocessing.Pool`` running on the cluster.

    ``processes`` only bounds in-flight concurrency (the cluster scheduler
    owns placement); ``initializer`` runs lazily inside each task via a
    per-process cache, mirroring Pool's per-worker initializer."""

    def __init__(
        self,
        processes: Optional[int] = None,
        initializer: Optional[Callable] = None,
        initargs: tuple = (),
    ):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._max_inflight = processes or int(
            ray_tpu.cluster_resources().get("CPU", 4)
        )
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

        import uuid as _uuid

        init = initializer
        pool_id = _uuid.uuid4().hex  # initializer runs once per (pool, worker)

        @ray_tpu.remote
        def _run(fn, args, kwargs, initargs):
            if init is not None:
                import ray_tpu.util.multiprocessing as _m

                done = getattr(_m, "_pool_initialized_ids", None)
                if done is None:
                    done = _m._pool_initialized_ids = set()
                if pool_id not in done:
                    init(*initargs)
                    done.add(pool_id)
            return fn(*args, **(kwargs or {}))

        self._task = _run

    # -- core ---------------------------------------------------------------

    def _submit(self, fn, args=(), kwargs=None):
        if self._closed:
            raise ValueError("Pool not running")
        return self._task.remote(fn, tuple(args), dict(kwargs or {}), self._initargs)

    def _submit_many(self, fn, iterable_of_args):
        """Windowed submission: at most ``processes`` tasks in flight."""
        refs = []
        window: list = []
        for args in iterable_of_args:
            if len(window) >= self._max_inflight:
                _, window = ray_tpu.wait(window, num_returns=1)
            r = self._submit(fn, args)
            window.append(r)
            refs.append(r)
        return refs

    # -- stdlib surface -----------------------------------------------------

    def apply(self, func, args=(), kwds=None):
        return ray_tpu.get(self._submit(func, args, kwds))

    def apply_async(self, func, args=(), kwds=None, callback=None, error_callback=None):
        return AsyncResult(
            [self._submit(func, args, kwds)], True, callback, error_callback
        )

    def map(self, func, iterable, chunksize: Optional[int] = None):
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize=None, callback=None, error_callback=None):
        refs = self._submit_many(func, ((x,) for x in iterable))
        return AsyncResult(refs, False, callback, error_callback)

    def starmap(self, func, iterable, chunksize: Optional[int] = None):
        return ray_tpu.get(self._submit_many(func, iterable))

    def starmap_async(self, func, iterable, chunksize=None, callback=None, error_callback=None):
        return AsyncResult(self._submit_many(func, iterable), False, callback, error_callback)

    def imap(self, func, iterable, chunksize: Optional[int] = None):
        refs = self._submit_many(func, ((x,) for x in iterable))
        for r in refs:
            yield ray_tpu.get(r)

    def imap_unordered(self, func, iterable, chunksize: Optional[int] = None):
        pending = self._submit_many(func, ((x,) for x in iterable))
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield ray_tpu.get(done[0])

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
