"""Per-request phase ledger: where a served LLM request's milliseconds go.

The task-plane waterfall (``util.waterfall``) breaks one *task hop* into
phases; this module does the same for one *LLM request* across its whole
life — proxy recv → router dispatch → engine queue → admission →
prefill → decode → stream delivery — so ``obs attribute`` can say which
phase owns the p99 instead of "the engine took 2s".

Design (PR 11 hot-path contract, ≤2µs/stamp):

* **Engine side** — every ``Request`` carries a tiny ledger: a plain
  float list ``[cursor, dur_0 .. dur_K]`` where ``cursor`` is the wall
  time of the last stamp and ``dur_i`` accumulates seconds attributed
  to engine phase ``i``. The one stamp primitive, :func:`charge`, is
  two float ops and two list stores — no locks, no allocation, no dict
  lookups (call sites pass the module's integer index constants). All
  ledger touches happen on the thread that owns the request at that
  moment (the submitter at submit, the step thread afterwards — the
  engine lock serializes the handoff), so the ledger is single-writer
  by construction. ``tests/test_obs_hotpath.py`` pins ``new_ledger`` /
  ``charge`` at zero transitive lock acquisitions.
* **Complete and non-overlapping by construction** — the cursor model
  attributes *every* interval from submit to finish to exactly one
  phase: each engine event charges "now − cursor" to its phase and
  advances the cursor. There is nothing to double-count and no gap to
  lose; the identity "Σ engine phases == finish − submit" is exact up
  to float rounding (``tests/test_llm_phases.py`` pins it across
  spec-decode, preemption recompute, failover resume and prefix hits).
* **Preemption is attributed, not lumped** — a preempted request's
  recompute (re-queue, re-admit, re-prefill) charges the ``preempt``
  phase via ``Request.phase_recompute``, never ``queue``/``prefill``,
  so recompute cost is visible as its own line.
* **Prefix-cache hits land in ``admit``** — admission performs the
  radix match and block sharing, so matched-prefix time is charged to
  ``admit`` by the cursor; ``prefill`` covers only the uncached suffix.
* **Proxy side** — the proxy stamps four wall-clock anchors (recv,
  dispatch, first chunk, done-sentinel receipt ≈ engine finish, fully
  written) and folds them at stream completion; the dispatch anchor
  additionally rides the request's sampled ``trace_ctx`` dict
  (``t_dispatch``) so the engine can observe the cross-process
  ``dispatch`` leg into the histogram family.
* **Failover resume never double-counts** — a resumed submit
  (``resume_tokens``) starts a FRESH ledger covering only the second
  attempt; already-delivered token phases are not re-charged, and the
  resumed engine skips the ``dispatch`` observe (its gap to the proxy
  dispatch anchor spans the dead attempt — ``obs attribute`` reports
  that interval as the ``failover`` component instead).

Clocks: stamps are ``time.time()`` so anchors compare across processes
on one host (same contract as ``util.waterfall``); a wall-clock step can
produce a negative leg, which folds clamp at zero. Cross-host proxy ↔
replica skew is absorbed into the ``dispatch``/``stream`` legs — the
engine-internal phases are single-clock and immune.

Export: the low-cardinality ``llm_request_phase_s{phase=…}`` histogram
family (fleet percentiles survive ring eviction) plus two recorder
events — ``llm.phase.ledger`` (engine fold at finish: the full
decomposition + submit/finish anchors) and ``llm.phase.proxy`` (proxy
fold at stream completion: the four anchors). ``obs attribute`` merges
both into per-request decompositions; ``RAY_TPU_PHASES=0`` disables
stamping entirely (the bench A/B arm).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ray_tpu._private import events as _events

#: the full phase registry — (name, owner, start → stop edges). Order is
#: the canonical report order; grafana's derived "request phases" row and
#: the OBSERVABILITY.md table are generated/checked against this, so a
#: renamed phase cannot drift. Owners: ``proxy`` (observed by the HTTP
#: proxy), ``engine`` (observed by the engine/scheduler under its step
#: lock), ``assembly`` (computed only by ``obs attribute`` from event
#: anchors — no histogram series).
PHASES = (
    ("proxy", "proxy",
     "HTTP request parsed → stream thread hands off to the router"),
    ("dispatch", "engine",
     "proxy dispatch anchor → engine submit (cross-process; skipped for "
     "resumed submits)"),
    ("queue", "engine", "engine submit → admission pops the request"),
    ("admit", "engine",
     "admission pop → slot installed (prefix match, evict-to-fit, shed "
     "check, CoW queue — matched-prefix time lands HERE, not prefill)"),
    ("cow_fork", "engine",
     "queued copy-on-write forks applied as a batched device copy"),
    ("prefill", "engine",
     "chunked prefill of the uncached suffix (inter-chunk waits included)"),
    ("decode", "engine",
     "plain decode steps (inter-token waits included)"),
    ("spec_verify", "engine",
     "speculative draft + verify decode steps"),
    ("preempt", "engine",
     "eviction under KV pressure + the whole recompute (re-queue, "
     "re-admit, re-prefill) until the slot is running again"),
    ("stream", "proxy",
     "engine finish (done-sentinel receipt) → response fully written"),
    ("failover", "assembly",
     "proxy dispatch → resumed engine submit when a replica died "
     "mid-stream (includes the lost attempt)"),
    ("total", "proxy", "HTTP request parsed → response fully written"),
)

#: engine-ledger phases in slot order — ledger index i+1 accumulates
#: ENGINE_PHASES[i]; the integer constants below are what the engine's
#: hot call sites pass to charge() (no per-stamp dict lookups)
ENGINE_PHASES = (
    "queue", "admit", "cow_fork", "prefill", "decode", "spec_verify",
    "preempt",
)
QUEUE, ADMIT, COW_FORK, PREFILL, DECODE, SPEC_VERIFY, PREEMPT = range(
    1, len(ENGINE_PHASES) + 1
)

#: raylint RL012 registries
METRIC_NAMES = ("llm_request_phase_s",)
EVENT_NAMES = ("llm.phase.ledger", "llm.phase.proxy")

#: sub-ms admission/queue legs up through multi-second decode tails —
#: the default metrics boundaries start at 5ms and would flatten the
#: engine-internal legs into one bucket
_PHASE_BOUNDARIES = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

#: per-phase tag dicts built once — folds run at every request finish
_PHASE_TAGS = {name: {"phase": name} for name, _o, _d in PHASES}

_METRICS = None
_METRICS_LOCK = threading.Lock()

#: module gate (``RAY_TPU_PHASES``, default on) — read once at import so
#: the bench A/B subprocess arms get an honest OFF; set_enabled() is the
#: in-process test hook
_ENABLED = os.environ.get("RAY_TPU_PHASES", "1") != "0"


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Flip the gate in-process (tests); returns the previous value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    return prev


def _metrics() -> dict:
    global _METRICS
    if _METRICS is not None:
        return _METRICS
    with _METRICS_LOCK:
        if _METRICS is not None:
            return _METRICS
        from ray_tpu.util.metrics import Histogram

        _METRICS = {
            "phase": Histogram(
                "llm_request_phase_s",
                "per-request latency attributed by phase (proxy/dispatch/"
                "queue/admit/cow_fork/prefill/decode/spec_verify/preempt/"
                "stream/total)",
                boundaries=_PHASE_BOUNDARIES,
                tag_keys=("phase",),
            ),
        }
    return _METRICS


# ---------------------------------------------------------------------------
# emit path (engine submit/step threads) — must stay lock-free
# ---------------------------------------------------------------------------


def new_ledger(t: float) -> list:
    """A fresh request ledger anchored at wall time ``t`` (the submit):
    ``[cursor, 0.0 × len(ENGINE_PHASES)]``."""
    led = [0.0] * (len(ENGINE_PHASES) + 1)
    led[0] = t
    return led


def charge(led: list, idx: int, now: float) -> None:
    """Attribute the interval since the last stamp to engine phase
    ``idx`` (one of the module's QUEUE..PREEMPT constants) and advance
    the cursor. Two float ops — the ≤2µs/stamp budget's whole cost."""
    led[idx] += now - led[0]
    led[0] = now


# ---------------------------------------------------------------------------
# fold paths (request finish — off the per-token path)
# ---------------------------------------------------------------------------


def fold_engine(req, now: float, reason: str) -> Optional[dict]:
    """Engine-side fold at finish (called under the engine lock, once
    per request): observe every non-zero engine phase into the histogram
    family and record the full decomposition + anchors as ONE
    ``llm.phase.ledger`` event. The caller has already charged the tail
    interval, so Σ phases == now − submit exactly."""
    led = req.phase_led
    if led is None:
        return None
    observe = _metrics()["phase"].observe
    decomp = {}
    for i, name in enumerate(ENGINE_PHASES):
        dur = led[i + 1]
        if dur < 0.0:
            dur = 0.0  # clamp wall-clock steps
        decomp[name] = round(dur, 6)
        if dur > 0.0:
            observe(dur, tags=_PHASE_TAGS[name])
    fields = dict(
        request_id=req.trace_id, engine_req=req.id, reason=reason,
        t_submit=round(req.arrival_t, 6), t_finish=round(now, 6),
        resumed=req.resumed_from, phases=decomp,
    )
    if req.phase_dispatch_s is not None:
        fields["dispatch_s"] = round(req.phase_dispatch_s, 6)
    _events.record("llm.phase.ledger", **fields)
    return decomp


def note_dispatch(req, ctx) -> None:
    """Engine-side at submit: when the request's sampled trace context
    carries the proxy's dispatch anchor, observe the cross-process
    ``dispatch`` leg. Resumed submits skip it — their gap to the anchor
    spans the dead attempt and belongs to ``failover`` (assembly)."""
    req.phase_dispatch_s = None
    if type(ctx) is not dict:
        return
    t_disp = ctx.get("t_dispatch")
    if t_disp is None or req.resumed_from:
        return
    dur = req.arrival_t - t_disp
    if dur < 0.0:
        dur = 0.0  # cross-process clock step: clamp, don't discard
    req.phase_dispatch_s = dur
    _metrics()["phase"].observe(dur, tags=_PHASE_TAGS["dispatch"])


def fold_proxy(
    request_id: str,
    t_recv: float,
    t_dispatch: Optional[float],
    t_first: Optional[float],
    t_finish: Optional[float],
    t_done: float,
    status: int = 200,
) -> None:
    """Proxy-side fold at stream completion: observe the proxy-owned
    legs (``proxy``, ``stream``, ``total``) and record the anchors as
    ONE ``llm.phase.proxy`` event — what ``obs attribute`` joins against
    the engine ledger(s) to compute ``dispatch``/``stream``/``failover``
    exactly. ``t_finish`` is the done-sentinel receipt (≈ engine finish
    plus one hop; the event-anchor join uses the engine's exact
    ``t_finish`` instead)."""
    observe = _metrics()["phase"].observe
    if t_dispatch is not None:
        observe(max(0.0, t_dispatch - t_recv), tags=_PHASE_TAGS["proxy"])
    if t_finish is not None:
        observe(max(0.0, t_done - t_finish), tags=_PHASE_TAGS["stream"])
    observe(max(0.0, t_done - t_recv), tags=_PHASE_TAGS["total"])
    fields = dict(
        request_id=request_id, status=status,
        t_recv=round(t_recv, 6), t_done=round(t_done, 6),
    )
    if t_dispatch is not None:
        fields["t_dispatch"] = round(t_dispatch, 6)
    if t_first is not None:
        fields["t_first"] = round(t_first, 6)
    if t_finish is not None:
        fields["t_finish"] = round(t_finish, 6)
    _events.record("llm.phase.proxy", **fields)
