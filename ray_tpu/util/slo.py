"""Declarative SLO rules and the burn-rate math behind them.

Reference shape: the SRE-workbook multi-window multi-burn-rate alerting
policy (fast window catches a cliff in minutes, slow window keeps a slow
leak from paging and from auto-resolving mid-incident), applied to the
cluster-merged metric time series ``util.metrics.collect_series`` produces.
This module is PURE — rules in, ``{breached, value, detail}`` out — so the
burn-rate math is golden-testable without a cluster; the stateful
fire/resolve machine lives in ``_private/alerts.py``.

Three rule kinds cover the default SLOs:

* ``histogram_burn`` — a latency SLO over a histogram metric: "``objective``
  of events complete within ``threshold`` seconds". Bad events per window =
  observations above the threshold bucket; burn rate = bad-fraction /
  error-budget. Fires when BOTH the fast and the slow window burn above
  their factors.
* ``counter_burn`` — an availability SLO over a tagged counter: bad events
  are the series matching ``bad_tags`` (e.g. ``status=5xx``), total is every
  series of the metric. Same multi-window burn evaluation.
* ``gauge_threshold`` — a saturation SLO: the gauge has been at/above
  ``threshold`` for ``for_s`` seconds continuously (KV-pool exhaustion).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

from ray_tpu.util import metrics as _m


@dataclasses.dataclass
class SLORule:
    name: str
    metric: str
    kind: str  # "histogram_burn" | "counter_burn" | "gauge_threshold"
    #: fraction of good events promised (burn kinds)
    objective: float = 0.99
    #: latency bound in seconds (histogram_burn) / gauge bound (gauge_threshold)
    threshold: float = 0.0
    #: tag subset selecting the BAD series of a counter_burn metric
    bad_tags: Optional[dict] = None
    #: tag subset selecting WHICH series of the metric the rule reads at
    #: all (histogram_burn over one member of a tagged family, e.g.
    #: ``llm_request_phase_s{phase=queue}``); None = every series
    tags: Optional[dict] = None
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    #: SRE-workbook page factors scaled to the in-memory retention window
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    #: gauge_threshold: how long the gauge must hold above threshold
    for_s: float = 0.0
    #: hysteresis: a firing alert resolves only after this long clean
    resolve_after_s: float = 60.0
    #: consumers key off these (the serve autoscaler reacts to
    #: ``{"serve": "upscale"}``)
    labels: dict = dataclasses.field(default_factory=dict)
    description: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def budget_burn(bad: float, total: float, objective: float) -> float:
    """Burn rate: observed bad fraction over the allowed bad fraction.
    1.0 = exactly spending budget at the sustainable pace; 0 when the
    window saw no events (no evidence is not an outage)."""
    if total <= 0:
        return 0.0
    budget = max(1e-9, 1.0 - objective)
    return (bad / total) / budget


def _tags_match(tagset: str, want: Optional[dict]) -> bool:
    if not want:
        return True
    try:
        tags = json.loads(tagset) if tagset else {}
    except ValueError:
        return False
    return all(tags.get(k) == v for k, v in want.items())


def _hist_bad_total(
    points: list, boundaries, threshold: float, window_s: float, now: float
) -> tuple[float, float]:
    delta = _m.hist_window_delta(points, window_s, now)
    if not delta:
        return 0.0, 0.0
    buckets, total = delta[:-2], delta[-1]
    good = sum(
        c for b, c in zip(boundaries or (), buckets) if float(b) <= threshold
    )
    return max(0.0, total - good), float(total)


def _counter_windows(
    series: dict, rule: "SLORule", window_s: float, now: float
) -> tuple[float, float]:
    bad = total = 0.0
    for tagset, points in series.items():
        delta = _m.series_window_delta(points, window_s, now) or 0.0
        total += delta
        if _tags_match(tagset, rule.bad_tags):
            bad += delta
    return bad, total


def evaluate_rule(rule: SLORule, merged: dict, now: Optional[float] = None) -> dict:
    """One evaluation of ``rule`` against ``merge_proc_series`` output.
    Returns ``{"breached": bool, "value": float, "detail": dict}`` where
    ``value`` is the fast-window burn rate (burn kinds) or the latest gauge
    reading (gauge_threshold)."""
    now = time.time() if now is None else now
    ent = merged.get(rule.metric)
    if ent is None:
        return {"breached": False, "value": 0.0, "detail": {"no_data": True}}
    series = ent.get("series", {})

    if rule.kind == "histogram_burn":
        bounds = ent.get("boundaries") or ()
        bf = bt = sf = st_ = 0.0
        for tagset, points in series.items():
            if not _tags_match(tagset, rule.tags):
                continue
            b, t = _hist_bad_total(points, bounds, rule.threshold,
                                   rule.fast_window_s, now)
            bf, bt = bf + b, bt + t
            b, t = _hist_bad_total(points, bounds, rule.threshold,
                                   rule.slow_window_s, now)
            sf, st_ = sf + b, st_ + t
        fast = budget_burn(bf, bt, rule.objective)
        slow = budget_burn(sf, st_, rule.objective)
        return {
            "breached": fast >= rule.fast_burn and slow >= rule.slow_burn,
            "value": fast,
            "detail": {"fast_burn": fast, "slow_burn": slow,
                       "bad_fast": bf, "total_fast": bt},
        }

    if rule.kind == "counter_burn":
        bf, bt = _counter_windows(series, rule, rule.fast_window_s, now)
        bs, bt_s = _counter_windows(series, rule, rule.slow_window_s, now)
        fast = budget_burn(bf, bt, rule.objective)
        slow = budget_burn(bs, bt_s, rule.objective)
        return {
            "breached": fast >= rule.fast_burn and slow >= rule.slow_burn,
            "value": fast,
            "detail": {"fast_burn": fast, "slow_burn": slow,
                       "bad_fast": bf, "total_fast": bt},
        }

    if rule.kind == "gauge_threshold":
        # newest reading across tagsets decides the value; breach requires
        # every sample of the trailing for_s window at/above the threshold
        # with coverage reaching back the full window
        best: Optional[tuple] = None
        for points in series.values():
            if points and (best is None or points[-1][0] > best[0]):
                best = points[-1]
                window = points
        if best is None:
            return {"breached": False, "value": 0.0, "detail": {"no_data": True}}
        value = float(best[1])
        if rule.for_s <= 0:
            breached = value >= rule.threshold
        else:
            # sustained: every sample inside the trailing for_s window is
            # at/above the threshold AND the last sample BEFORE the window
            # was too (coverage proof — a gauge that only just spiked has
            # no sample that old and must not page yet)
            start = now - rule.for_s
            in_window = [(ts, float(v)) for ts, v in window if ts > start]
            older = [float(v) for ts, v in window if ts <= start]
            breached = (
                bool(in_window)
                and all(v >= rule.threshold for _ts, v in in_window)
                and bool(older)
                and older[-1] >= rule.threshold
            )
        return {"breached": breached, "value": value, "detail": {}}

    raise ValueError(f"unknown SLO rule kind {rule.kind!r}")


# ---------------------------------------------------------------------------
# default rules (env-tunable so tests and small clusters can retune windows
# without code changes)
# ---------------------------------------------------------------------------


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def default_rules() -> list[SLORule]:
    """The shipped SLOs: TTFT p99 latency, serve request availability, and
    KV-pool saturation. Windows default to 60s/300s — sized to the
    in-memory series retention, not the workbook's 5m/1h."""
    fast = _envf("RAY_TPU_SLO_FAST_WINDOW_S", 60.0)
    slow = _envf("RAY_TPU_SLO_SLOW_WINDOW_S", 300.0)
    resolve = _envf("RAY_TPU_SLO_RESOLVE_AFTER_S", 60.0)
    return [
        SLORule(
            name="ttft-p99",
            metric="llm_time_to_first_token_s",
            kind="histogram_burn",
            objective=_envf("RAY_TPU_SLO_TTFT_OBJECTIVE", 0.99),
            threshold=_envf("RAY_TPU_SLO_TTFT_THRESHOLD_S", 2.5),
            fast_window_s=fast,
            slow_window_s=slow,
            fast_burn=_envf("RAY_TPU_SLO_FAST_BURN", 14.4),
            slow_burn=_envf("RAY_TPU_SLO_SLOW_BURN", 6.0),
            resolve_after_s=resolve,
            labels={"serve": "upscale", "severity": "page"},
            description="99% of requests reach their first token within the "
                        "threshold; both burn windows above factor pages.",
        ),
        SLORule(
            name="queue-time-burn",
            metric="llm_request_phase_s",
            kind="histogram_burn",
            tags={"phase": "queue"},
            objective=_envf("RAY_TPU_SLO_QUEUE_OBJECTIVE", 0.99),
            threshold=_envf("RAY_TPU_SLO_QUEUE_THRESHOLD_S", 1.0),
            fast_window_s=fast,
            slow_window_s=slow,
            fast_burn=_envf("RAY_TPU_SLO_FAST_BURN", 14.4),
            slow_burn=_envf("RAY_TPU_SLO_SLOW_BURN", 6.0),
            resolve_after_s=resolve,
            labels={"serve": "upscale", "severity": "page"},
            description="99% of requests spend under the threshold waiting "
                        "in the engine queue (phase ledger's queue leg) — "
                        "queue burn is the capacity signal: it pages and "
                        "asks the autoscaler for replicas BEFORE TTFT "
                        "breaches, because queueing is where overload "
                        "lands first (the loadgen overload arm is the "
                        "reproduction).",
        ),
        SLORule(
            name="request-errors",
            metric="serve_requests",
            kind="counter_burn",
            objective=_envf("RAY_TPU_SLO_ERROR_OBJECTIVE", 0.99),
            bad_tags={"status": "5xx"},
            fast_window_s=fast,
            slow_window_s=slow,
            fast_burn=_envf("RAY_TPU_SLO_FAST_BURN", 14.4),
            slow_burn=_envf("RAY_TPU_SLO_SLOW_BURN", 6.0),
            resolve_after_s=resolve,
            labels={"severity": "page"},
            description="99% of proxied requests succeed (non-5xx).",
        ),
        SLORule(
            name="kv-pool-exhaustion",
            metric="llm_kv_block_utilization",
            kind="gauge_threshold",
            threshold=_envf("RAY_TPU_SLO_KV_THRESHOLD", 0.97),
            for_s=_envf("RAY_TPU_SLO_KV_FOR_S", 30.0),
            resolve_after_s=resolve,
            labels={"serve": "upscale", "severity": "warn"},
            description="Paged-KV pool pinned at/above the threshold long "
                        "enough that preemption thrash is imminent.",
        ),
        SLORule(
            name="rlhf-staleness",
            metric="rlhf_weights_staleness",
            kind="gauge_threshold",
            # the async RLHF learner publishes the mean version-age of
            # every batch it consumes; sustained high staleness means
            # weight pushes are not landing on the rollout engines
            # (object-plane backlog, dead rollout actor, learner
            # outrunning generation) and the importance correction is
            # carrying more off-policy drift than the trust region wants
            threshold=_envf("RAY_TPU_SLO_RLHF_STALENESS", 8.0),
            for_s=_envf("RAY_TPU_SLO_RLHF_STALENESS_FOR_S", 30.0),
            resolve_after_s=resolve,
            labels={"severity": "warn"},
            description="RLHF trajectories consumed by the learner are "
                        "persistently many weight versions stale — the "
                        "rollout plane is falling behind the sync push.",
        ),
        SLORule(
            name="retrace-storm",
            metric="device_retraces",
            kind="counter_burn",
            # EVERY retrace is a bad event (bad_tags None selects all
            # series): a jit site recompiling after its warmup baseline
            # (util.device_prof — RL014's runtime twin) pays a full
            # XLA compile mid-traffic, so any nonzero window rate burns
            # the whole budget and fires while the storm is live; zero
            # retraces is the steady state and evaluates as no-evidence
            objective=_envf("RAY_TPU_SLO_RETRACE_OBJECTIVE", 0.99),
            fast_window_s=fast,
            slow_window_s=slow,
            fast_burn=_envf("RAY_TPU_SLO_FAST_BURN", 14.4),
            slow_burn=_envf("RAY_TPU_SLO_SLOW_BURN", 6.0),
            resolve_after_s=resolve,
            labels={"severity": "warn"},
            description="A jitted entry point (decode/prefill/verify/"
                        "fork/train step) is RECOMPILING after warmup — "
                        "static shapes are broken somewhere; each retrace "
                        "stalls every request in the batch for a compile.",
        ),
        SLORule(
            name="engine-stall",
            metric="llm_watchdog_step_age_s",
            kind="gauge_threshold",
            # the watchdog (llm.watchdog) publishes the age of the last
            # engine step while work is pending, 0 when idle/healthy — a
            # sustained non-zero age is a wedged step loop, the whole
            # replica's streams frozen at once
            threshold=_envf("RAY_TPU_SLO_STALL_S", 30.0),
            for_s=_envf("RAY_TPU_SLO_STALL_FOR_S", 10.0),
            resolve_after_s=resolve,
            labels={"severity": "page"},
            description="LLM engine step loop has made no progress with "
                        "work pending — streams are frozen; the watchdog's "
                        "llm.watchdog.stall event carries the diagnosis.",
        ),
        SLORule(
            name="arena-pressure",
            metric="core_arena_occupancy",
            kind="gauge_threshold",
            # the head publishes the WORST node's arena used/capacity
            # ratio (ISSUE 19 object ledger); sustained occupancy above
            # the bound means puts are about to degrade to the inline
            # path (agents) or start spilling (head) — check obs arena
            # for the node and obs objects for what holds the bytes
            threshold=_envf("RAY_TPU_SLO_ARENA_OCCUPANCY", 0.9),
            for_s=_envf("RAY_TPU_SLO_ARENA_FOR_S", 30.0),
            resolve_after_s=resolve,
            labels={"severity": "warn"},
            description="A node's object arena is sustained at/above the "
                        "occupancy bound — zero-copy puts are about to "
                        "degrade (agent inline fallback / head spilling).",
        ),
        SLORule(
            name="spill-burn",
            metric="core_object_spills",
            kind="counter_burn",
            # EVERY spill is a bad event (bad_tags None selects all
            # series): each one is a full serialize-to-disk round trip
            # plus a restore on next access, so a sustained window rate
            # burns the whole budget while the thrash is live; zero
            # spills is the steady state and evaluates as no-evidence
            objective=_envf("RAY_TPU_SLO_SPILL_OBJECTIVE", 0.99),
            fast_window_s=fast,
            slow_window_s=slow,
            fast_burn=_envf("RAY_TPU_SLO_FAST_BURN", 14.4),
            slow_burn=_envf("RAY_TPU_SLO_SLOW_BURN", 6.0),
            resolve_after_s=resolve,
            labels={"severity": "warn"},
            description="The head is spilling directory objects to disk "
                        "under arena pressure — the working set no longer "
                        "fits; every get of a spilled object pays a "
                        "restore round trip.",
        ),
    ]
