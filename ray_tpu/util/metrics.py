"""User-defined metrics: Counter / Gauge / Histogram.

Reference: ``python/ray/util/metrics.py`` (the user-facing wrappers over the
C++ OpenCensus stats pipeline, ``src/ray/stats/metric_defs.cc``). TPU-first
shape: no per-node metrics agent daemon — each process records locally and a
daemon flusher publishes aggregated snapshots into the head's KV store under
``__metrics__/<process-tag>``; ``collect()`` merges all snapshots, giving
every driver/worker a cluster-wide view through the control plane that
already exists. ``prometheus_text()`` renders the standard exposition format
for scraping.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict
from typing import Optional, Sequence

_FLUSH_INTERVAL_S = 2.0
_KV_PREFIX = "__metrics__/"

_registry_lock = threading.Lock()
_registry: list["Metric"] = []
_flusher_started = False


def _tag_key(tags: Optional[dict]) -> str:
    if not tags:
        return ""
    return json.dumps(dict(sorted(tags.items())), separators=(",", ":"))


class Metric:
    """Base: named, tagged, locally aggregated."""

    kind = "metric"

    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name or any(c in name for c in " /"):
            raise ValueError(f"Invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        self._data: dict[str, float | list] = defaultdict(float)
        with _registry_lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: dict) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[dict]) -> str:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"Unknown tag(s) {sorted(extra)} for metric {self.name!r}")
        return _tag_key(merged)

    def _snapshot(self) -> dict:
        with self._lock:
            snap = {
                "name": self.name,
                "kind": self.kind,
                "description": self.description,
                "data": {k: v for k, v in self._data.items()},
            }
            bounds = getattr(self, "boundaries", None)
            if bounds is not None:
                snap["boundaries"] = list(bounds)
            return snap


class Counter(Metric):
    """Monotonically increasing count (reference: util/metrics.py Counter)."""

    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires a non-negative value")
        key = self._tags(tags)
        with self._lock:
            self._data[key] += value


class Gauge(Metric):
    """Last-value-wins measurement."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[dict] = None):
        key = self._tags(tags)
        with self._lock:
            self._data[key] = float(value)


DEFAULT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(Metric):
    """Bucketed distribution; records per-bucket counts + sum + count."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = DEFAULT_BOUNDARIES,
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(sorted(boundaries))

    def observe(self, value: float, tags: Optional[dict] = None):
        key = self._tags(tags)
        with self._lock:
            cur = self._data.get(key)
            if not isinstance(cur, list):
                cur = [0] * (len(self.boundaries) + 1) + [0.0, 0]  # buckets+sum+count
                self._data[key] = cur
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            cur[idx] += 1
            cur[-2] += value
            cur[-1] += 1

    record = observe  # reference alias

    def percentiles(
        self, qs: Sequence[float] = (0.5, 0.95, 0.99), tags: Optional[dict] = None
    ) -> dict:
        """This PROCESS's distribution snapshot: ``{"p50": ..., "p95": ...,
        "count": n, "sum": s}`` (bucket interpolation —
        :func:`percentiles_from_buckets`). Cluster-wide: ``histogram_percentiles``."""
        key = self._tags(tags)
        with self._lock:
            cur = self._data.get(key)
            data = list(cur) if isinstance(cur, list) else None
        return _percentile_summary(self.boundaries, data, qs)


def percentiles_from_buckets(
    boundaries: Sequence[float], counts: Sequence[float], q: float
) -> float:
    """Quantile estimate from histogram buckets, Prometheus
    ``histogram_quantile`` style: linear interpolation inside the target
    bucket; the overflow (+Inf) bucket clamps to the highest boundary (no
    upper bound to interpolate toward). ``counts`` is the per-bucket
    (non-cumulative) layout ``observe()`` maintains — one slot per
    boundary plus overflow."""
    total = sum(counts)
    if total <= 0:
        return float("nan")
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(boundaries):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            frac = (rank - prev) / max(counts[i], 1e-12)
            return lo + (b - lo) * min(max(frac, 0.0), 1.0)
        lo = b
    return float(boundaries[-1]) if boundaries else float("nan")


def _percentile_summary(
    boundaries: Sequence[float], data: Optional[list], qs: Sequence[float]
) -> dict:
    if not data:
        out = {f"p{round(q * 100) if q < 1 else 100}": float("nan") for q in qs}
        out.update(count=0, sum=0.0)
        return out
    buckets, total, s = data[:-2], data[-1], data[-2]
    out = {
        f"p{round(q * 100) if q < 1 else 100}": percentiles_from_buckets(
            boundaries, buckets, q
        )
        for q in qs
    }
    out.update(count=int(total), sum=float(s))
    return out


def histogram_percentiles(
    name: Optional[str] = None, qs: Sequence[float] = (0.5, 0.95, 0.99)
) -> dict:
    """CLUSTER-wide percentile snapshots from ``collect()``'s merged
    buckets: ``{metric_name: {tagset: {"p50": ..., "count": ...}}}``
    (optionally one metric). What ``obs top`` renders for TTFT/ITL."""
    data = collect()
    out: dict[str, dict] = {}
    for mname, series in data.get("metrics", {}).items():
        if data.get("kinds", {}).get(mname) != "histogram":
            continue
        if name is not None and mname != name:
            continue
        bounds = tuple(data.get("boundaries", {}).get(mname, ()))
        out[mname] = {
            tagset: _percentile_summary(bounds, val, qs)
            for tagset, val in series.items()
            if isinstance(val, list)
        }
    return out


# ---------------------------------------------------------------------------
# publication + collection
# ---------------------------------------------------------------------------


def _process_tag() -> str:
    return f"pid-{os.getpid()}"


def flush() -> None:
    """Publish this process's metric snapshots into the head KV."""
    from ray_tpu._private.runtime import get_ctx

    try:
        ctx = get_ctx()
    except Exception:
        return  # not initialized (yet/anymore) — metrics are best-effort
    with _registry_lock:
        snaps = [m._snapshot() for m in _registry]
    if not snaps:
        return
    try:
        ctx.call(
            "kv_put",
            key=_KV_PREFIX + _process_tag(),
            value=json.dumps({"time": time.time(), "metrics": snaps}).encode(),
        )
    except Exception:
        pass  # head gone (shutdown) — metrics are best-effort


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            flush()

    threading.Thread(target=loop, daemon=True, name="metrics-flusher").start()
    atexit.register(flush)


def collect() -> dict:
    """Cluster-wide merged view: {metric_name: {tagset: value-or-histogram}}.

    Counters/histograms sum across processes; gauges last-write-wins by
    publish time.
    """
    from ray_tpu._private.runtime import get_ctx

    flush()
    try:
        ctx = get_ctx()
    except Exception:
        return {}
    keys = ctx.call("kv_keys", prefix=_KV_PREFIX)
    snapshots = []
    for key in keys:
        raw = ctx.call("kv_get", key=key)
        if raw:
            snapshots.append(json.loads(raw.decode()))
    snapshots.sort(key=lambda s: s["time"])
    merged: dict[str, dict] = {}
    kinds: dict[str, str] = {}
    boundaries: dict[str, list] = {}
    helps: dict[str, str] = {}
    for snap in snapshots:
        for m in snap["metrics"]:
            name, kind = m["name"], m["kind"]
            kinds[name] = kind
            if m.get("description"):
                helps[name] = m["description"]
            if "boundaries" in m:
                boundaries[name] = m["boundaries"]
            out = merged.setdefault(name, {})
            for tagset, val in m["data"].items():
                if kind == "gauge":
                    out[tagset] = val
                elif kind == "counter":
                    out[tagset] = out.get(tagset, 0.0) + val
                else:  # histogram: elementwise sum
                    prev = out.get(tagset)
                    out[tagset] = (
                        [a + b for a, b in zip(prev, val)] if prev else list(val)
                    )
    return {
        "kinds": kinds, "metrics": merged, "boundaries": boundaries,
        "help": helps,
    }


def _escape_label(v) -> str:
    # exposition format: backslash, double-quote and newline are escaped
    # inside label values
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_num(v) -> str:
    # canonical sample values: integers bare, floats via repr (shortest
    # round-trippable form — Prometheus parses either)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def prometheus_text() -> str:
    """Render collect() in the Prometheus exposition format: ``# HELP`` /
    ``# TYPE`` per family, escaped label values, and histograms as
    CUMULATIVE ``_bucket{le="..."}`` series (ending at ``le="+Inf"`` ==
    ``_count``) plus ``_sum``/``_count`` — parseable by any exposition
    parser (tests re-parse the output to prove it)."""
    data = collect()
    lines = []
    for name, series in data.get("metrics", {}).items():
        kind = data["kinds"].get(name, "counter")
        prom_kind = {"gauge": "gauge", "histogram": "histogram"}.get(kind, "counter")
        help_text = data.get("help", {}).get(name, "")
        if help_text:
            esc = help_text.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP ray_tpu_{name} {esc}")
        lines.append(f"# TYPE ray_tpu_{name} {prom_kind}")
        bounds = data.get("boundaries", {}).get(name, [])
        for tagset, val in series.items():
            tags = json.loads(tagset) if tagset else {}

            def fmt(extra=None):
                merged_tags = dict(tags)
                if extra:
                    merged_tags.update(extra)
                if not merged_tags:
                    return ""
                return (
                    "{"
                    + ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in merged_tags.items()
                    )
                    + "}"
                )

            if isinstance(val, list):
                cum = 0
                for b, count in zip(bounds, val):
                    cum += count
                    lines.append(
                        f'ray_tpu_{name}_bucket{fmt({"le": _fmt_num(b)})} '
                        f"{_fmt_num(cum)}"
                    )
                lines.append(
                    f'ray_tpu_{name}_bucket{fmt({"le": "+Inf"})} {_fmt_num(val[-1])}'
                )
                lines.append(f"ray_tpu_{name}_sum{fmt()} {_fmt_num(val[-2])}")
                lines.append(f"ray_tpu_{name}_count{fmt()} {_fmt_num(val[-1])}")
            else:
                lines.append(f"ray_tpu_{name}{fmt()} {_fmt_num(val)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# core runtime metrics (reference: src/ray/stats/metric_defs.cc — tasks by
# state, actors, object store usage — exported by the C++ runtime; here a
# lightweight sampler thread reads the head's state API into gauges so
# Grafana boards generated by util.grafana have live core series)
# ---------------------------------------------------------------------------

_core_thread: Optional[threading.Thread] = None
_core_stop = threading.Event()


_core_gauges: Optional[dict] = None


def _get_core_gauges() -> dict:
    """The 8 core gauges, created ONCE per process: a start/stop/start cycle
    must reuse them, or each restart would append duplicates to _registry
    whose stale snapshots fight the live ones in collect()'s merge."""
    global _core_gauges
    if _core_gauges is None:
        _core_gauges = {
            "tasks": Gauge("core_tasks", "tasks by scheduler state", ("state",)),
            "actors": Gauge("core_actors", "actors by FSM state", ("state",)),
            "nodes": Gauge("core_nodes", "alive nodes"),
            "res_used": Gauge("core_resource_used", "used logical resources", ("resource",)),
            "res_total": Gauge("core_resource_total", "total logical resources", ("resource",)),
            "objects": Gauge("core_objects", "objects tracked by the head"),
            "object_bytes": Gauge("core_object_bytes", "bytes of tracked objects"),
            "spilled": Gauge("core_spilled_bytes", "bytes spilled to disk"),
        }
    return _core_gauges


def _set_tagged(gauge: "Gauge", tag_key: str, values: dict) -> None:
    """Set every current tagged value and ZERO previously-seen tags that
    vanished this sample — a state with no tasks reports 0, not its last
    nonzero value forever."""
    seen = getattr(gauge, "_core_seen", set())
    for tag, v in values.items():
        gauge.set(v, tags={tag_key: tag})
    for tag in seen - set(values):
        gauge.set(0, tags={tag_key: tag})
    gauge._core_seen = seen | set(values)


def start_core_metrics(interval_s: float = 5.0) -> None:
    """Start (idempotently) the core-series sampler in this process. The
    dashboard server calls this; drivers can too for headless scraping."""
    global _core_thread
    if _core_thread is not None and _core_thread.is_alive():
        return
    _core_stop.clear()
    g = _get_core_gauges()

    def _sample_once() -> None:
        import ray_tpu
        from ray_tpu.util import state as st

        summary = st.summary()
        _set_tagged(g["tasks"], "state", summary.get("tasks", {}).get("by_state") or {})
        _set_tagged(g["actors"], "state", summary.get("actors", {}).get("by_state") or {})
        g["nodes"].set(
            len([n for n in st.list_nodes() if n.get("Alive", n.get("alive", True))])
        )
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        _set_tagged(g["res_total"], "resource", total)
        _set_tagged(
            g["res_used"],
            "resource",
            {k: v - avail.get(k, 0.0) for k, v in total.items()},
        )
        objs = summary.get("objects", {})
        g["objects"].set(objs.get("total", 0))
        g["object_bytes"].set(objs.get("total_bytes", 0))
        g["spilled"].set(objs.get("spilled_bytes", 0))

    def _loop() -> None:
        while not _core_stop.wait(interval_s):
            try:
                _sample_once()
            except Exception:  # raylint: disable=RL007
                # head shutting down / not initialized: keep polling; the
                # sampler must never take the process down, and warning here
                # would fire on every clean driver shutdown
                pass

    try:
        _sample_once()
    except Exception:
        pass
    _core_thread = threading.Thread(
        target=_loop, name="core-metrics", daemon=True
    )
    _core_thread.start()


def stop_core_metrics() -> None:
    global _core_thread
    t = _core_thread
    _core_stop.set()
    _core_thread = None
    if t is not None:
        # join before a restart can clear the event, or the old sampler
        # (mid-sample when the flag flipped) keeps running alongside the new
        t.join(timeout=10.0)
