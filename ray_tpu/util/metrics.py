"""User-defined metrics: Counter / Gauge / Histogram.

Reference: ``python/ray/util/metrics.py`` (the user-facing wrappers over the
C++ OpenCensus stats pipeline, ``src/ray/stats/metric_defs.cc``). TPU-first
shape: no per-node metrics agent daemon — each process records locally and a
daemon flusher publishes aggregated snapshots into the head's KV store under
``__metrics__/<process-tag>``; ``collect()`` merges all snapshots, giving
every driver/worker a cluster-wide view through the control plane that
already exists. ``prometheus_text()`` renders the standard exposition format
for scraping.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict, deque
from typing import Optional, Sequence

_FLUSH_INTERVAL_S = 2.0
_KV_PREFIX = "__metrics__/"

_registry_lock = threading.Lock()
_registry: list["Metric"] = []
_flusher_started = False


def _series_enabled() -> bool:
    return os.environ.get("RAY_TPU_METRICS_SERIES", "1").lower() not in (
        "0", "false", "off",
    )


def _series_capacity() -> int:
    try:
        return max(8, int(os.environ.get("RAY_TPU_METRICS_SERIES_CAPACITY", "512")))
    except ValueError:
        return 512


def _series_interval() -> float:
    try:
        return max(
            0.05, float(os.environ.get("RAY_TPU_METRICS_SERIES_INTERVAL_S", "1.0"))
        )
    except ValueError:
        return 1.0


def _tag_key(tags: Optional[dict]) -> str:
    if not tags:
        return ""
    return json.dumps(dict(sorted(tags.items())), separators=(",", ":"))


class Metric:
    """Base: named, tagged, locally aggregated.

    Hot-path architecture (PR-11 rebuild; OBSERVABILITY.md): increments
    land in **per-thread cells** — each emitting thread owns a private
    dict it alone mutates, registered once by an atomic ``list.append``.
    The emit path (``Counter.inc`` / ``Gauge.set`` /
    ``Histogram.observe``) therefore acquires NO shared lock, ever; the
    cells are merged only at snapshot time (the flusher's 1 Hz sample or
    an explicit ``collect()``), where all the aggregation cost lives.
    ``self._lock`` guards nothing on the emit path — it serializes
    snapshot-side compaction only."""

    kind = "metric"

    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name or any(c in name for c in " /"):
            raise ValueError(f"Invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        self._data: dict[str, float | list] = defaultdict(float)
        self._tls = threading.local()
        # (owner thread, cell) per emitting thread. Appended lock-free at
        # first emit; dead threads' cells are folded into _data and
        # removed at snapshot time (under _lock) so thread churn — e.g.
        # serve's per-stream proxy threads — cannot grow this unboundedly
        self._cells: list[tuple] = []
        with _registry_lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: dict) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[dict]) -> str:
        if not tags and not self._default_tags:
            return ""  # untagged fast path: no dict build, no set math
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"Unknown tag(s) {sorted(extra)} for metric {self.name!r}")
        return _tag_key(merged)

    def _cell(self) -> dict:
        """This thread's private cell. First touch registers it via a
        plain list.append — atomic under the GIL, no lock (the raylint
        hot-path fixture asserts the emit path stays lock-free)."""
        try:
            return self._tls.cell
        except AttributeError:
            cell: dict = {}
            self._cells.append((threading.current_thread(), cell))
            self._tls.cell = cell
            return cell

    @staticmethod
    def _fold_into(out: dict, cell: dict) -> None:
        for k, v in cell.copy().items():
            if isinstance(v, list):  # histogram vector: elementwise sum
                prev = out.get(k)
                out[k] = (
                    [a + b for a, b in zip(prev, v)]
                    if isinstance(prev, list)
                    else list(v)
                )
            else:  # counter cell: sum
                out[k] = out.get(k, 0.0) + v

    def _merged_data(self) -> dict:
        """Base data + every thread cell, merged by kind (caller holds
        ``self._lock``). Cells are single-writer dicts; ``dict.copy`` is
        an atomic C call, so the merge sees a consistent point-in-time
        view of each cell. Cells whose owner thread has exited are folded
        PERMANENTLY into ``_data`` and dropped from the list — the owner
        can never write again, so the fold is exact, and per-stream /
        per-request threads can't leak cells for the process lifetime.
        (The lock serializes concurrent snapshots: without it two folds
        of the same dead cell would double-count.)"""
        for entry in list(self._cells):
            thread, cell = entry
            if not thread.is_alive():
                self._fold_into(self._data, cell)
                try:
                    self._cells.remove(entry)
                except ValueError:
                    pass
        out = dict(self._data)
        for _thread, cell in list(self._cells):
            self._fold_into(out, cell)
        return out

    def _snapshot(self) -> dict:
        with self._lock:
            snap = {
                "name": self.name,
                "kind": self.kind,
                "description": self.description,
                "data": self._merged_data(),
            }
            bounds = getattr(self, "boundaries", None)
            if bounds is not None:
                snap["boundaries"] = list(bounds)
            return snap


class Counter(Metric):
    """Monotonically increasing count (reference: util/metrics.py Counter).

    ``inc`` is lock-free: the increment lands in the calling thread's
    private cell (single-writer dict read-modify-write — exact), merged
    into the published total only at snapshot/flush time."""

    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires a non-negative value")
        key = self._tags(tags)
        try:
            cell = self._tls.cell
        except AttributeError:
            cell = self._cell()
        cell[key] = cell.get(key, 0.0) + value


class Gauge(Metric):
    """Last-value-wins measurement. ``set`` is a single atomic dict store
    into the shared data — last write wins by definition, so thread cells
    would only blur which write was last; no lock needed either way."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[dict] = None):
        key = self._tags(tags)
        self._data[key] = float(value)


DEFAULT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(Metric):
    """Bucketed distribution; records per-bucket counts + sum + count.

    ``observe`` is lock-free like ``Counter.inc``: the bucket vector
    lives in the calling thread's cell (single-writer, exact); snapshot
    merges vectors elementwise. A reader copying a cell mid-observe can
    see a vector whose bucket is bumped but whose count isn't yet — a
    one-sample transient the next snapshot corrects (same tolerance
    Prometheus scrapes have always had)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = DEFAULT_BOUNDARIES,
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(sorted(boundaries))

    def observe(self, value: float, tags: Optional[dict] = None):
        key = self._tags(tags)
        try:
            cell = self._tls.cell
        except AttributeError:
            cell = self._cell()
        cur = cell.get(key)
        if not isinstance(cur, list):
            cur = [0] * (len(self.boundaries) + 1) + [0.0, 0]  # buckets+sum+count
            cell[key] = cur
        idx = len(self.boundaries)
        for i, b in enumerate(self.boundaries):
            if value <= b:
                idx = i
                break
        cur[idx] += 1
        cur[-2] += value
        cur[-1] += 1

    record = observe  # reference alias

    def percentiles(
        self, qs: Sequence[float] = (0.5, 0.95, 0.99), tags: Optional[dict] = None
    ) -> dict:
        """This PROCESS's distribution snapshot: ``{"p50": ..., "p95": ...,
        "count": n, "sum": s}`` (bucket interpolation —
        :func:`percentiles_from_buckets`). Cluster-wide: ``histogram_percentiles``."""
        key = self._tags(tags)
        with self._lock:
            cur = self._merged_data().get(key)
            data = list(cur) if isinstance(cur, list) else None
        return _percentile_summary(self.boundaries, data, qs)


def safe_counter(name: str, description: str = "") -> Optional["Counter"]:
    """A ``Counter``, or None when the registry is unavailable (late
    interpreter teardown, import cycles). The shared shape for LAZY drop
    counters created off the hot path on first drop — tracing's
    ``tracing_dropped_spans`` and the flight recorder's
    ``events_dropped`` both construct through here."""
    try:
        return Counter(name, description)
    except Exception:
        return None


def percentiles_from_buckets(
    boundaries: Sequence[float], counts: Sequence[float], q: float
) -> float:
    """Quantile estimate from histogram buckets, Prometheus
    ``histogram_quantile`` style: linear interpolation inside the target
    bucket; the overflow (+Inf) bucket clamps to the highest boundary (no
    upper bound to interpolate toward). ``counts`` is the per-bucket
    (non-cumulative) layout ``observe()`` maintains — one slot per
    boundary plus overflow."""
    total = sum(counts)
    if total <= 0:
        return float("nan")
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(boundaries):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            frac = (rank - prev) / max(counts[i], 1e-12)
            return lo + (b - lo) * min(max(frac, 0.0), 1.0)
        lo = b
    return float(boundaries[-1]) if boundaries else float("nan")


def _percentile_summary(
    boundaries: Sequence[float], data: Optional[list], qs: Sequence[float]
) -> dict:
    if not data:
        out = {f"p{round(q * 100) if q < 1 else 100}": float("nan") for q in qs}
        out.update(count=0, sum=0.0)
        return out
    buckets, total, s = data[:-2], data[-1], data[-2]
    out = {
        f"p{round(q * 100) if q < 1 else 100}": percentiles_from_buckets(
            boundaries, buckets, q
        )
        for q in qs
    }
    out.update(count=int(total), sum=float(s))
    return out


def histogram_percentiles(
    name: Optional[str] = None, qs: Sequence[float] = (0.5, 0.95, 0.99)
) -> dict:
    """CLUSTER-wide percentile snapshots from ``collect()``'s merged
    buckets: ``{metric_name: {tagset: {"p50": ..., "count": ...}}}``
    (optionally one metric). What ``obs top`` renders for TTFT/ITL."""
    data = collect()
    out: dict[str, dict] = {}
    for mname, series in data.get("metrics", {}).items():
        if data.get("kinds", {}).get(mname) != "histogram":
            continue
        if name is not None and mname != name:
            continue
        bounds = tuple(data.get("boundaries", {}).get(mname, ()))
        out[mname] = {
            tagset: _percentile_summary(bounds, val, qs)
            for tagset, val in series.items()
            if isinstance(val, list)
        }
    return out


# ---------------------------------------------------------------------------
# time series: a bounded in-process ring per (metric, tagset)
#
# Every process samples its OWN registry on a fixed cadence into fixed-size
# rings, and flush() ships only the not-yet-shipped samples to the head
# (``series_push`` — the same mailbox rendezvous the snapshot KV uses), where
# a bounded per-process store holds recent history.  ``collect_series()``
# merges the per-process series into one cluster view; rates/percentiles are
# derived at query time (``series_rate`` / ``series_window_delta`` /
# ``series_percentiles_over_window``) with Prometheus-style counter-reset
# handling, so ``obs top`` can show a real tokens/s and the SLO engine can
# evaluate burn rates over real windows without any external TSDB.
# ---------------------------------------------------------------------------

_series_lock = threading.Lock()
# name -> {"kind": str, "boundaries": list|None, "points": {tagset: deque}}
# deque entries: (sample_seq, ts, value) — value is a float for
# counters/gauges, the buckets+sum+count list for histograms
_series: dict[str, dict] = {}
_sample_seq = 0
_shipped_seq = 0


def _merged_local_snaps(snaps: list[dict]) -> dict[str, dict]:
    """Fold one process's registry snapshots into one entry per metric NAME
    (two same-name Metric objects in one process — e.g. re-created across
    test runs — must produce ONE sample per tick, merged with collect()'s
    semantics, not two appends that would corrupt the ring)."""
    out: dict[str, dict] = {}
    for snap in snaps:
        name, kind = snap["name"], snap["kind"]
        ent = out.setdefault(
            name,
            {"kind": kind, "boundaries": snap.get("boundaries"), "data": {}},
        )
        for tagset, val in snap["data"].items():
            if kind == "gauge":
                ent["data"][tagset] = val
            elif kind == "counter":
                ent["data"][tagset] = ent["data"].get(tagset, 0.0) + val
            else:
                prev = ent["data"].get(tagset)
                ent["data"][tagset] = (
                    [a + b for a, b in zip(prev, val)] if prev else list(val)
                )
    return out


def sample_series_now(now: Optional[float] = None) -> int:
    """Append one sample per (metric, tagset) to this process's rings.
    Called by the flusher thread on its cadence; tests and ``obs top
    --once`` call it directly for a deterministic sample."""
    global _sample_seq
    if not _series_enabled():
        return 0
    now = time.time() if now is None else now
    with _registry_lock:
        snaps = [m._snapshot() for m in _registry]
    merged = _merged_local_snaps(snaps)
    cap = _series_capacity()
    with _series_lock:
        _sample_seq += 1
        seq = _sample_seq
        for name, snap in merged.items():
            ent = _series.setdefault(
                name, {"kind": snap["kind"], "boundaries": None, "points": {}}
            )
            ent["kind"] = snap["kind"]
            if snap.get("boundaries") is not None:
                ent["boundaries"] = list(snap["boundaries"])
            for tagset, val in snap["data"].items():
                dq = ent["points"].get(tagset)
                if dq is None or dq.maxlen != cap:
                    dq = deque(dq or (), maxlen=cap)
                    ent["points"][tagset] = dq
                dq.append(
                    (seq, now, list(val) if isinstance(val, list) else float(val))
                )
    return seq


def get_local_series(name: Optional[str] = None) -> dict:
    """This PROCESS's rings as plain lists (oldest first)."""
    with _series_lock:
        out = {}
        for n, ent in _series.items():
            if name is not None and n != name:
                continue
            out[n] = {
                "kind": ent["kind"],
                "boundaries": ent["boundaries"],
                "points": {
                    tagset: [[ts, v] for (_seq, ts, v) in dq]
                    for tagset, dq in ent["points"].items()
                },
            }
        return out


def configure_series(capacity: Optional[int] = None) -> None:
    """Resize the per-process rings (tests/tuning; drops nothing unless
    shrinking)."""
    if capacity is not None:
        os.environ["RAY_TPU_METRICS_SERIES_CAPACITY"] = str(int(capacity))
        with _series_lock:
            for ent in _series.values():
                for tagset, dq in list(ent["points"].items()):
                    ent["points"][tagset] = deque(dq, maxlen=max(8, int(capacity)))


def _reset_series_for_tests() -> None:
    global _sample_seq, _shipped_seq
    with _series_lock:
        _series.clear()
        _sample_seq = 0
        _shipped_seq = 0


_ship_lock = threading.Lock()
# off-caller-path shipping rendezvous: callers that need fresh data at the
# head (collect_series) RAISE this condition instead of shipping inline;
# the flusher thread performs the I/O. Two sequence numbers make the
# handoff race-free: a waiter is satisfied only by a ship that STARTED
# after its request (the flusher claims _ship_req_seq BEFORE shipping and
# publishes it to _ship_done_seq after) — a request landing mid-ship is
# NOT consumed by that in-flight ship; the next loop pass ships again.
_ship_cv = threading.Condition()
_ship_req_seq = 0   # bumped by request_ship()
_ship_done_seq = 0  # last req seq fully shipped (flusher-owned)


def request_ship(wait: bool = False, timeout: float = 2.0) -> None:
    """Ask the flusher thread to run a ship pass NOW (and optionally wait
    for it to finish). This is the ONLY way query paths interact with
    series shipping — the telemetry I/O itself always runs on the
    dedicated flusher thread, never on the caller (PR-11 contract: no
    application thread blocks on telemetry I/O it didn't ask for).
    Falls back to an inline ship only when no flusher exists (a process
    that never created a metric has nothing to ship anyway)."""
    global _ship_req_seq
    if not _series_enabled():
        return
    if not _flusher_started:
        _ship_series()  # no flusher thread to hand off to
        return
    with _ship_cv:
        _ship_req_seq += 1
        mine = _ship_req_seq
        _ship_cv.notify_all()
        if wait:
            _ship_cv.wait_for(lambda: _ship_done_seq >= mine, timeout=timeout)


def _ship_series() -> None:
    """Push samples recorded since the last successful ship to the head's
    SeriesStore. Best-effort, like the KV snapshot flush. Runs on the
    flusher thread (``request_ship``) — plus inline at interpreter exit,
    the one moment there may be no flusher left to hand off to.

    Delivery is IDEMPOTENT: rows carry their sample seq and the head drops
    anything at/below its per-process watermark, so a push whose reply was
    lost (head applied it, caller retries the backlog) cannot duplicate
    rows; ``_ship_lock`` additionally serializes concurrent shippers (the
    flusher thread racing an exit-time flush would otherwise have the
    same backlog in flight twice)."""
    global _shipped_seq
    if not _series_enabled():
        return
    if not _ship_lock.acquire(blocking=False):
        return  # another thread is shipping this same backlog right now
    try:
        with _series_lock:
            if _sample_seq == _shipped_seq:
                return
            floor = _shipped_seq
            top = _sample_seq
            payload: dict[str, dict] = {}
            for name, ent in _series.items():
                rows = {}
                for tagset, dq in ent["points"].items():
                    new = [[seq, ts, v] for (seq, ts, v) in dq if seq > floor]
                    if new:
                        rows[tagset] = new
                if rows:
                    payload[name] = {"kind": ent["kind"], "points": rows}
                    if ent["boundaries"] is not None:
                        payload[name]["boundaries"] = ent["boundaries"]
        if not payload:
            with _series_lock:
                _shipped_seq = max(_shipped_seq, top)
            return
        from ray_tpu._private.runtime import get_ctx

        try:
            ctx = get_ctx()
            ctx.call(
                "series_push",
                proc=_process_tag(),
                interval=_series_interval(),
                series=payload,
            )
        except Exception:
            return  # head gone / not initialized — retry backlog next flush
        with _series_lock:
            _shipped_seq = max(_shipped_seq, top)
    finally:
        _ship_lock.release()


class SeriesStore:
    """Head-side bounded store of per-process metric series.

    ``push`` appends one process's incremental samples; each (proc, metric,
    tagset) keeps at most ``capacity`` samples, so memory is bounded no
    matter the uptime. ``raw()`` is the drain format ``collect_series``
    merges client-side; the head's alert evaluator merges in-process."""

    _MAX_PROCS = 256

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity or _series_capacity()
        # proc -> {"interval": float, "t": last-push, "metrics": {name: ent}}
        self._procs: dict[str, dict] = {}

    def push(self, proc: str, interval: float, series: dict) -> None:
        with self._lock:
            rec = self._procs.get(proc)
            if rec is None:
                if len(self._procs) >= self._MAX_PROCS:
                    oldest = min(self._procs, key=lambda p: self._procs[p]["t"])
                    del self._procs[oldest]
                rec = self._procs[proc] = {
                    "interval": interval, "metrics": {}, "seq": -1,
                }
            rec["interval"] = float(interval)
            rec["t"] = time.time()
            watermark = rec.get("seq", -1)
            top = watermark
            for name, ent in series.items():
                dest = rec["metrics"].setdefault(
                    name,
                    {"kind": ent["kind"], "boundaries": ent.get("boundaries"),
                     "points": {}},
                )
                dest["kind"] = ent["kind"]
                if ent.get("boundaries") is not None:
                    dest["boundaries"] = ent["boundaries"]
                for tagset, rows in ent["points"].items():
                    dq = dest["points"].get(tagset)
                    if dq is None:
                        dq = dest["points"][tagset] = deque(maxlen=self._capacity)
                    for row in rows:
                        if len(row) == 3:  # [seq, ts, v]: idempotent delivery
                            seq, ts, v = row
                            if seq <= watermark:
                                continue  # re-delivered after a lost reply
                            top = max(top, seq)
                        else:  # bare [ts, v] (tests / external feeders)
                            ts, v = row
                        dq.append((float(ts), v))
            rec["seq"] = top

    def raw(self, name: Optional[str] = None) -> dict:
        with self._lock:
            out: dict[str, dict] = {}
            for proc, rec in self._procs.items():
                metrics = {}
                for n, ent in rec["metrics"].items():
                    if name is not None and n != name:
                        continue
                    metrics[n] = {
                        "kind": ent["kind"],
                        "boundaries": ent["boundaries"],
                        "points": {
                            tagset: [[ts, v] for ts, v in dq]
                            for tagset, dq in ent["points"].items()
                        },
                    }
                if metrics:
                    out[proc] = {"interval": rec["interval"], "metrics": metrics}
            return out

    def merged(self, name: Optional[str] = None) -> dict:
        return merge_proc_series(self.raw(name))


def merge_proc_series(raw: dict) -> dict:
    """Merge per-process series into one cluster view, binned on the
    coarsest contributing sample interval: counters and histograms are
    forward-filled per process then summed (a process that missed a bin
    contributes its last known cumulative value, and a dead process's
    contribution freezes instead of vanishing — the merged counter stays
    monotonic through stragglers); gauges are last-write-wins by sample
    time, mirroring ``collect()``. Returns ``{name: {"kind", "boundaries",
    "series": {tagset: [(ts, value), ...]}}}``."""
    # (name, tagset) -> list of (per-proc sorted samples); plus metadata
    grouped: dict[str, dict] = {}
    for proc, rec in raw.items():
        interval = max(float(rec.get("interval", 1.0)), 0.05)
        for name, ent in rec.get("metrics", {}).items():
            g = grouped.setdefault(
                name,
                {"kind": ent["kind"], "boundaries": ent.get("boundaries"),
                 "interval": interval, "tagsets": {}},
            )
            g["interval"] = max(g["interval"], interval)
            if ent.get("boundaries") is not None:
                g["boundaries"] = ent["boundaries"]
            for tagset, rows in ent["points"].items():
                g["tagsets"].setdefault(tagset, []).append(
                    sorted((float(ts), v) for ts, v in rows)
                )
    out: dict[str, dict] = {}
    for name, g in grouped.items():
        series = {}
        for tagset, proc_samples in g["tagsets"].items():
            series[tagset] = _merge_one(proc_samples, g["kind"], g["interval"])
        out[name] = {
            "kind": g["kind"], "boundaries": g["boundaries"], "series": series,
        }
    return out


def _merge_one(proc_samples: list[list], kind: str, width: float) -> list[tuple]:
    if len(proc_samples) == 1:
        return list(proc_samples[0])
    bins = sorted({int(ts // width) for samples in proc_samples for ts, _v in samples})
    merged: list[tuple] = []
    cursors = [0] * len(proc_samples)
    last_val: list = [None] * len(proc_samples)
    for b in bins:
        end = (b + 1) * width
        bin_ts = None
        gauge_pick = None  # (ts, value) with max ts in bin
        for i, samples in enumerate(proc_samples):
            c = cursors[i]
            while c < len(samples) and samples[c][0] < end:
                ts, v = samples[c]
                last_val[i] = v
                if ts >= b * width:
                    bin_ts = ts if bin_ts is None else max(bin_ts, ts)
                    if gauge_pick is None or ts >= gauge_pick[0]:
                        gauge_pick = (ts, v)
                c += 1
            cursors[i] = c
        if bin_ts is None:
            continue  # no process sampled inside this bin
        if kind == "gauge":
            merged.append((bin_ts, gauge_pick[1]))
        elif kind == "histogram":
            total = None
            for v in last_val:
                if v is None:
                    continue
                total = list(v) if total is None else [a + b2 for a, b2 in zip(total, v)]
            merged.append((bin_ts, total))
        else:  # counter: sum of forward-filled cumulative values
            merged.append((bin_ts, sum(v for v in last_val if v is not None)))
    return merged


# ---- query helpers over merged (ts, value) sample lists -------------------


def series_rate(points: list) -> list[tuple]:
    """Per-interval rate from consecutive cumulative samples, with counter
    resets handled Prometheus-style (a decrease means the counter restarted
    from zero, so the post-reset value IS the increase)."""
    out = []
    prev = None
    for ts, v in points:
        if prev is not None:
            pts, pv = prev
            dt = ts - pts
            if dt > 0:
                delta = v - pv
                if delta < 0:
                    delta = v
                out.append((ts, delta / dt))
        prev = (ts, v)
    return out


def latest_rate(points: list):
    """Rate of the newest sample pair, or None with fewer than 2 samples —
    the ``obs top`` contract (render ``—``, never a lifetime-average)."""
    rates = series_rate(points[-2:] if len(points) >= 2 else points)
    return rates[-1][1] if rates else None


def series_window_delta(points: list, window_s: float, now: Optional[float] = None):
    """Reset-aware increase of a cumulative counter over the trailing
    window (the sample just before the window start is the baseline).
    Returns None when the window holds no step."""
    now = time.time() if now is None else now
    start = now - window_s
    total = None
    prev = None
    for ts, v in points:
        if prev is not None and ts > start:
            delta = v - prev
            if delta < 0:
                delta = v
            total = delta if total is None else total + delta
        prev = v
    return total


def hist_window_delta(points: list, window_s: float, now: Optional[float] = None):
    """Elementwise increase of a histogram's buckets+sum+count vector over
    the trailing window (reset-aware: a shrinking count restarts the
    baseline). None when no in-window step exists."""
    now = time.time() if now is None else now
    start = now - window_s
    total = None
    prev = None
    for ts, v in points:
        if prev is not None and ts > start:
            if v[-1] < prev[-1]:  # counter reset: the new vector IS the delta
                delta = list(v)
            else:
                delta = [a - b for a, b in zip(v, prev)]
            total = delta if total is None else [a + b for a, b in zip(total, delta)]
        prev = v
    return total


def series_percentiles_over_window(
    points: list,
    boundaries: Sequence[float],
    window_s: float,
    qs: Sequence[float] = (0.5, 0.95, 0.99),
    now: Optional[float] = None,
) -> dict:
    """Percentile summary of a histogram series restricted to the trailing
    window — what ``obs series`` and the TTFT SLO rule evaluate."""
    delta = hist_window_delta(points, window_s, now)
    return _percentile_summary(tuple(boundaries or ()), delta, qs)


def collect_series(name: Optional[str] = None) -> dict:
    """Cluster-wide merged time series from the head's SeriesStore (after
    shipping this process's own backlog). Same return shape as
    ``merge_proc_series``. Deliberately does NOT take a fresh sample: the
    background sampler's evenly spaced ticks are what make delta/dt rates
    meaningful — a collect-time sample would end every series with a
    near-zero interval and rate the newest pair at ~0."""
    from ray_tpu._private.runtime import get_ctx

    request_ship(wait=True)
    try:
        ctx = get_ctx()
        raw = ctx.call("series_get", name=name)
    except Exception:
        raw = None
    if raw is None:
        raw = {
            _process_tag(): {
                "interval": _series_interval(),
                "metrics": get_local_series(name),
            }
        }
    return merge_proc_series(raw)


# ---------------------------------------------------------------------------
# publication + collection
# ---------------------------------------------------------------------------


def _process_tag() -> str:
    return f"pid-{os.getpid()}"


def flush(ship_inline: bool = False) -> None:
    """Publish this process's metric snapshots into the head KV. Series
    shipping is handed to the flusher thread (``request_ship``) unless
    ``ship_inline`` — the exit-time path, where the flusher may already
    be dead and this is the backlog's last chance off the process."""
    from ray_tpu._private.runtime import get_ctx

    try:
        ctx = get_ctx()
    except Exception:
        return  # not initialized (yet/anymore) — metrics are best-effort
    with _registry_lock:
        snaps = [m._snapshot() for m in _registry]
    if not snaps:
        return
    try:
        ctx.call(
            "kv_put",
            key=_KV_PREFIX + _process_tag(),
            value=json.dumps({"time": time.time(), "metrics": snaps}).encode(),
        )
    except Exception:
        pass  # head gone (shutdown) — metrics are best-effort
    if ship_inline:
        _ship_series()
    else:
        # hand the I/O to the flusher thread but keep flush()'s contract
        # ("my samples are at the head when this returns") by waiting on
        # the rendezvous — bounded, and never from a submission path
        request_ship(wait=True)


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        # one thread does every off-path job on its own cadence: sample
        # the registry into the series rings every _series_interval()
        # (env, re-read each tick so tests can retune a live process),
        # ship snapshots + new samples every _FLUSH_INTERVAL_S, and
        # answer request_ship() nudges immediately — the condition wait
        # doubles as the tick sleep, so an on-demand ship never waits a
        # full interval
        global _ship_done_seq
        last_flush = 0.0
        last_sample = time.monotonic()
        while True:
            interval = _series_interval() if _series_enabled() else _FLUSH_INTERVAL_S
            with _ship_cv:
                if _ship_req_seq == _ship_done_seq:
                    _ship_cv.wait(timeout=max(0.01, last_sample + interval - time.monotonic()))
                # claim BEFORE the ship: requests arriving after this
                # read stay pending and trigger another pass
                claimed = _ship_req_seq
            now = time.monotonic()
            if now - last_sample >= interval:
                last_sample = now
                sample_series_now()
            if now - last_flush >= _FLUSH_INTERVAL_S:
                last_flush = now
                flush(ship_inline=True)
            elif claimed > _ship_done_seq:
                _ship_series()
            if claimed > _ship_done_seq:
                with _ship_cv:
                    _ship_done_seq = claimed
                    _ship_cv.notify_all()

    threading.Thread(target=loop, daemon=True, name="metrics-flusher").start()
    atexit.register(flush, ship_inline=True)


def collect() -> dict:
    """Cluster-wide merged view: {metric_name: {tagset: value-or-histogram}}.

    Counters/histograms sum across processes; gauges last-write-wins by
    publish time.
    """
    from ray_tpu._private.runtime import get_ctx

    flush()
    try:
        ctx = get_ctx()
    except Exception:
        return {}
    keys = ctx.call("kv_keys", prefix=_KV_PREFIX)
    snapshots = []
    for key in keys:
        raw = ctx.call("kv_get", key=key)
        if raw:
            snapshots.append(json.loads(raw.decode()))
    snapshots.sort(key=lambda s: s["time"])
    merged: dict[str, dict] = {}
    kinds: dict[str, str] = {}
    boundaries: dict[str, list] = {}
    helps: dict[str, str] = {}
    for snap in snapshots:
        for m in snap["metrics"]:
            name, kind = m["name"], m["kind"]
            kinds[name] = kind
            if m.get("description"):
                helps[name] = m["description"]
            if "boundaries" in m:
                boundaries[name] = m["boundaries"]
            out = merged.setdefault(name, {})
            for tagset, val in m["data"].items():
                if kind == "gauge":
                    out[tagset] = val
                elif kind == "counter":
                    out[tagset] = out.get(tagset, 0.0) + val
                else:  # histogram: elementwise sum
                    prev = out.get(tagset)
                    out[tagset] = (
                        [a + b for a, b in zip(prev, val)] if prev else list(val)
                    )
    return {
        "kinds": kinds, "metrics": merged, "boundaries": boundaries,
        "help": helps,
    }


def _escape_label(v) -> str:
    # exposition format: backslash, double-quote and newline are escaped
    # inside label values
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_num(v) -> str:
    # canonical sample values: integers bare, floats via repr (shortest
    # round-trippable form — Prometheus parses either)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def prometheus_text() -> str:
    """Render collect() in the Prometheus exposition format: ``# HELP`` /
    ``# TYPE`` per family, escaped label values, and histograms as
    CUMULATIVE ``_bucket{le="..."}`` series (ending at ``le="+Inf"`` ==
    ``_count``) plus ``_sum``/``_count`` — parseable by any exposition
    parser (tests re-parse the output to prove it)."""
    data = collect()
    lines = []
    for name, series in data.get("metrics", {}).items():
        kind = data["kinds"].get(name, "counter")
        prom_kind = {"gauge": "gauge", "histogram": "histogram"}.get(kind, "counter")
        help_text = data.get("help", {}).get(name, "")
        if help_text:
            esc = help_text.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP ray_tpu_{name} {esc}")
        lines.append(f"# TYPE ray_tpu_{name} {prom_kind}")
        bounds = data.get("boundaries", {}).get(name, [])
        for tagset, val in series.items():
            tags = json.loads(tagset) if tagset else {}

            def fmt(extra=None):
                merged_tags = dict(tags)
                if extra:
                    merged_tags.update(extra)
                if not merged_tags:
                    return ""
                return (
                    "{"
                    + ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in merged_tags.items()
                    )
                    + "}"
                )

            if isinstance(val, list):
                cum = 0
                for b, count in zip(bounds, val):
                    cum += count
                    lines.append(
                        f'ray_tpu_{name}_bucket{fmt({"le": _fmt_num(b)})} '
                        f"{_fmt_num(cum)}"
                    )
                lines.append(
                    f'ray_tpu_{name}_bucket{fmt({"le": "+Inf"})} {_fmt_num(val[-1])}'
                )
                lines.append(f"ray_tpu_{name}_sum{fmt()} {_fmt_num(val[-2])}")
                lines.append(f"ray_tpu_{name}_count{fmt()} {_fmt_num(val[-1])}")
            else:
                lines.append(f"ray_tpu_{name}{fmt()} {_fmt_num(val)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# core runtime metrics (reference: src/ray/stats/metric_defs.cc — tasks by
# state, actors, object store usage — exported by the C++ runtime; here a
# lightweight sampler thread reads the head's state API into gauges so
# Grafana boards generated by util.grafana have live core series)
# ---------------------------------------------------------------------------

_core_thread: Optional[threading.Thread] = None
_core_stop = threading.Event()


_core_gauges: Optional[dict] = None


def _get_core_gauges() -> dict:
    """The 8 core gauges, created ONCE per process: a start/stop/start cycle
    must reuse them, or each restart would append duplicates to _registry
    whose stale snapshots fight the live ones in collect()'s merge."""
    global _core_gauges
    if _core_gauges is None:
        _core_gauges = {
            "tasks": Gauge("core_tasks", "tasks by scheduler state", ("state",)),
            "actors": Gauge("core_actors", "actors by FSM state", ("state",)),
            "nodes": Gauge("core_nodes", "alive nodes"),
            "res_used": Gauge("core_resource_used", "used logical resources", ("resource",)),
            "res_total": Gauge("core_resource_total", "total logical resources", ("resource",)),
            "objects": Gauge("core_objects", "objects tracked by the head"),
            "object_bytes": Gauge("core_object_bytes", "bytes of tracked objects"),
            "spilled": Gauge("core_spilled_bytes", "bytes spilled to disk"),
        }
    return _core_gauges


def _set_tagged(gauge: "Gauge", tag_key: str, values: dict) -> None:
    """Set every current tagged value and ZERO previously-seen tags that
    vanished this sample — a state with no tasks reports 0, not its last
    nonzero value forever."""
    seen = getattr(gauge, "_core_seen", set())
    for tag, v in values.items():
        gauge.set(v, tags={tag_key: tag})
    for tag in seen - set(values):
        gauge.set(0, tags={tag_key: tag})
    gauge._core_seen = seen | set(values)


def start_core_metrics(interval_s: float = 5.0) -> None:
    """Start (idempotently) the core-series sampler in this process. The
    dashboard server calls this; drivers can too for headless scraping."""
    global _core_thread
    if _core_thread is not None and _core_thread.is_alive():
        return
    _core_stop.clear()
    g = _get_core_gauges()

    def _sample_once() -> None:
        import ray_tpu
        from ray_tpu.util import state as st

        summary = st.summary()
        _set_tagged(g["tasks"], "state", summary.get("tasks", {}).get("by_state") or {})
        _set_tagged(g["actors"], "state", summary.get("actors", {}).get("by_state") or {})
        g["nodes"].set(
            len([n for n in st.list_nodes() if n.get("Alive", n.get("alive", True))])
        )
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        _set_tagged(g["res_total"], "resource", total)
        _set_tagged(
            g["res_used"],
            "resource",
            {k: v - avail.get(k, 0.0) for k, v in total.items()},
        )
        objs = summary.get("objects", {})
        g["objects"].set(objs.get("total", 0))
        g["object_bytes"].set(objs.get("total_bytes", 0))
        g["spilled"].set(objs.get("spilled_bytes", 0))

    def _loop() -> None:
        while not _core_stop.wait(interval_s):
            try:
                _sample_once()
            except Exception:  # raylint: disable=RL007
                # head shutting down / not initialized: keep polling; the
                # sampler must never take the process down, and warning here
                # would fire on every clean driver shutdown
                pass

    try:
        _sample_once()
    except Exception:
        pass
    _core_thread = threading.Thread(
        target=_loop, name="core-metrics", daemon=True
    )
    _core_thread.start()


def stop_core_metrics() -> None:
    global _core_thread
    t = _core_thread
    _core_stop.set()
    _core_thread = None
    if t is not None:
        # join before a restart can clear the event, or the old sampler
        # (mid-sample when the flag flipped) keeps running alongside the new
        t.join(timeout=10.0)
