"""User-defined metrics: Counter / Gauge / Histogram.

Reference: ``python/ray/util/metrics.py`` (the user-facing wrappers over the
C++ OpenCensus stats pipeline, ``src/ray/stats/metric_defs.cc``). TPU-first
shape: no per-node metrics agent daemon — each process records locally and a
daemon flusher publishes aggregated snapshots into the head's KV store under
``__metrics__/<process-tag>``; ``collect()`` merges all snapshots, giving
every driver/worker a cluster-wide view through the control plane that
already exists. ``prometheus_text()`` renders the standard exposition format
for scraping.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict
from typing import Optional, Sequence

_FLUSH_INTERVAL_S = 2.0
_KV_PREFIX = "__metrics__/"

_registry_lock = threading.Lock()
_registry: list["Metric"] = []
_flusher_started = False


def _tag_key(tags: Optional[dict]) -> str:
    if not tags:
        return ""
    return json.dumps(dict(sorted(tags.items())), separators=(",", ":"))


class Metric:
    """Base: named, tagged, locally aggregated."""

    kind = "metric"

    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        if not name or any(c in name for c in " /"):
            raise ValueError(f"Invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        self._data: dict[str, float | list] = defaultdict(float)
        with _registry_lock:
            _registry.append(self)
        _ensure_flusher()

    def set_default_tags(self, tags: dict) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[dict]) -> str:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"Unknown tag(s) {sorted(extra)} for metric {self.name!r}")
        return _tag_key(merged)

    def _snapshot(self) -> dict:
        with self._lock:
            snap = {
                "name": self.name,
                "kind": self.kind,
                "description": self.description,
                "data": {k: v for k, v in self._data.items()},
            }
            bounds = getattr(self, "boundaries", None)
            if bounds is not None:
                snap["boundaries"] = list(bounds)
            return snap


class Counter(Metric):
    """Monotonically increasing count (reference: util/metrics.py Counter)."""

    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[dict] = None):
        if value < 0:
            raise ValueError("Counter.inc() requires a non-negative value")
        key = self._tags(tags)
        with self._lock:
            self._data[key] += value


class Gauge(Metric):
    """Last-value-wins measurement."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[dict] = None):
        key = self._tags(tags)
        with self._lock:
            self._data[key] = float(value)


DEFAULT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(Metric):
    """Bucketed distribution; records per-bucket counts + sum + count."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Sequence[float] = DEFAULT_BOUNDARIES,
        tag_keys: Sequence[str] = (),
    ):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(sorted(boundaries))

    def observe(self, value: float, tags: Optional[dict] = None):
        key = self._tags(tags)
        with self._lock:
            cur = self._data.get(key)
            if not isinstance(cur, list):
                cur = [0] * (len(self.boundaries) + 1) + [0.0, 0]  # buckets+sum+count
                self._data[key] = cur
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            cur[idx] += 1
            cur[-2] += value
            cur[-1] += 1

    record = observe  # reference alias


# ---------------------------------------------------------------------------
# publication + collection
# ---------------------------------------------------------------------------


def _process_tag() -> str:
    return f"pid-{os.getpid()}"


def flush() -> None:
    """Publish this process's metric snapshots into the head KV."""
    from ray_tpu._private.runtime import get_ctx

    try:
        ctx = get_ctx()
    except Exception:
        return  # not initialized (yet/anymore) — metrics are best-effort
    with _registry_lock:
        snaps = [m._snapshot() for m in _registry]
    if not snaps:
        return
    try:
        ctx.call(
            "kv_put",
            key=_KV_PREFIX + _process_tag(),
            value=json.dumps({"time": time.time(), "metrics": snaps}).encode(),
        )
    except Exception:
        pass  # head gone (shutdown) — metrics are best-effort


def _ensure_flusher():
    global _flusher_started
    with _registry_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def loop():
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            flush()

    threading.Thread(target=loop, daemon=True, name="metrics-flusher").start()
    atexit.register(flush)


def collect() -> dict:
    """Cluster-wide merged view: {metric_name: {tagset: value-or-histogram}}.

    Counters/histograms sum across processes; gauges last-write-wins by
    publish time.
    """
    from ray_tpu._private.runtime import get_ctx

    flush()
    try:
        ctx = get_ctx()
    except Exception:
        return {}
    keys = ctx.call("kv_keys", prefix=_KV_PREFIX)
    snapshots = []
    for key in keys:
        raw = ctx.call("kv_get", key=key)
        if raw:
            snapshots.append(json.loads(raw.decode()))
    snapshots.sort(key=lambda s: s["time"])
    merged: dict[str, dict] = {}
    kinds: dict[str, str] = {}
    boundaries: dict[str, list] = {}
    for snap in snapshots:
        for m in snap["metrics"]:
            name, kind = m["name"], m["kind"]
            kinds[name] = kind
            if "boundaries" in m:
                boundaries[name] = m["boundaries"]
            out = merged.setdefault(name, {})
            for tagset, val in m["data"].items():
                if kind == "gauge":
                    out[tagset] = val
                elif kind == "counter":
                    out[tagset] = out.get(tagset, 0.0) + val
                else:  # histogram: elementwise sum
                    prev = out.get(tagset)
                    out[tagset] = (
                        [a + b for a, b in zip(prev, val)] if prev else list(val)
                    )
    return {"kinds": kinds, "metrics": merged, "boundaries": boundaries}


def prometheus_text() -> str:
    """Render collect() in the Prometheus exposition format (histograms as
    cumulative ``_bucket{le=...}`` series + ``_sum``/``_count``)."""
    data = collect()
    lines = []
    for name, series in data.get("metrics", {}).items():
        kind = data["kinds"].get(name, "counter")
        prom_kind = {"gauge": "gauge", "histogram": "histogram"}.get(kind, "counter")
        lines.append(f"# TYPE ray_tpu_{name} {prom_kind}")
        bounds = data.get("boundaries", {}).get(name, [])
        for tagset, val in series.items():
            tags = json.loads(tagset) if tagset else {}

            def fmt(extra=None):
                merged_tags = dict(tags)
                if extra:
                    merged_tags.update(extra)
                if not merged_tags:
                    return ""
                return "{" + ",".join(f'{k}="{v}"' for k, v in merged_tags.items()) + "}"

            if isinstance(val, list):
                cum = 0
                for b, count in zip(bounds, val):
                    cum += count
                    lines.append(f'ray_tpu_{name}_bucket{fmt({"le": b})} {cum}')
                lines.append(f'ray_tpu_{name}_bucket{fmt({"le": "+Inf"})} {val[-1]}')
                lines.append(f"ray_tpu_{name}_sum{fmt()} {val[-2]}")
                lines.append(f"ray_tpu_{name}_count{fmt()} {val[-1]}")
            else:
                lines.append(f"ray_tpu_{name}{fmt()} {val}")
    return "\n".join(lines) + "\n"
