"""State API: structured introspection of cluster state.

Reference: ``python/ray/util/state/api.py`` (``list_tasks``/``list_actors``/
``list_objects``/``list_nodes``/``list_placement_groups``, ``summarize_*``)
and ``_private/state.py:924`` (``ray timeline`` Chrome-trace export). The
head's live tables and its ``task_events`` feed (``_private/head.py:244``)
are the single source of truth; this module is the read-side.

Use from any driver/worker attached to a cluster::

    from ray_tpu.util import state
    state.list_tasks()                  # [{'task_id':…,'state':…,'name':…}]
    state.summarize_tasks()             # counts by state
    state.timeline("/tmp/trace.json")   # chrome://tracing importable
"""

from __future__ import annotations

import json
from collections import Counter, defaultdict
from typing import Any, Optional


def _ctx():
    from ray_tpu._private.runtime import get_ctx

    ctx = get_ctx()
    if ctx is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init() first")
    return ctx


def list_tasks() -> list[dict]:
    """Live (not-yet-finished) tasks with their scheduling state."""
    return _ctx().call("list_tasks")


def list_actors() -> list[dict]:
    """All actors with lifecycle state, name, class and node."""
    return _ctx().call("list_actors")


def list_objects() -> list[dict]:
    """Objects in the store: size, readiness, refcount, pin count."""
    return _ctx().call("list_objects")


def list_nodes() -> list[dict]:
    """Cluster membership with total/available resources."""
    return _ctx().call("nodes")


def list_placement_groups() -> list[dict]:
    return _ctx().call("list_placement_groups")


def get_task_events() -> list[dict]:
    """The raw task state-transition feed (bounded ring, newest last)."""
    return _ctx().call("task_events")


def get_node_stats() -> dict:
    """Per-node /proc samples: cpu/mem/disk/load (reference: the
    dashboard reporter agent's psutil stats)."""
    return _ctx().call("node_stats")


def get_worker_stacks(timeout: float = 5.0) -> dict:
    """All-thread stack dumps of every worker (SIGUSR1 → faulthandler;
    works on wedged workers — reference: dashboard py-spy dumps).
    Returns {node: {pid: stacks_text}} with 'local' for the head host."""
    return _ctx().call("worker_stacks", timeout=timeout)


def profile_workers(duration_s: float = 2.0, interval_ms: float = 10.0) -> dict:
    """Sampling CPU profile of every live worker for ``duration_s``
    (reference: the dashboard's py-spy ``cpu_profile`` endpoint). Returns
    ``{node: {pid: collapsed_stacks}}`` — each value is flamegraph.pl /
    speedscope-ready collapsed-stack text, hottest stack first."""
    return _ctx().call(
        "worker_profile", duration_s=duration_s, interval_ms=interval_ms
    )


def get_alerts(eval_now: bool = False) -> list[dict]:
    """The head's SLO burn-rate engine state: one dict per rule with
    ``status`` (OK/FIRING/RESOLVED), current ``value``, ``since``, and
    ``labels``. ``eval_now`` forces an evaluation pass first."""
    return _ctx().call("alerts", eval_now=eval_now)


# ---------------------------------------------------------------------------
# summaries (reference: `ray summary tasks/actors/objects`)
# ---------------------------------------------------------------------------


def summarize_tasks() -> dict:
    events = get_task_events()
    per_task: dict[str, str] = {}
    names: dict[str, Optional[str]] = {}
    for ev in events:
        per_task[ev["task_id"]] = ev["state"]
        names[ev["task_id"]] = ev.get("name")
    for t in list_tasks():  # still-live tasks override their event state
        per_task[t["task_id"]] = t["state"]
        names[t["task_id"]] = t.get("name")
    by_state = Counter(per_task.values())
    by_func: dict[str, Counter] = defaultdict(Counter)
    for tid, st in per_task.items():
        by_func[names.get(tid) or "<unknown>"][st] += 1
    return {
        "total": len(per_task),
        "by_state": dict(by_state),
        "by_func": {k: dict(v) for k, v in sorted(by_func.items())},
    }


def summarize_actors() -> dict:
    actors = list_actors()
    return {
        "total": len(actors),
        "by_state": dict(Counter(a["state"] for a in actors)),
        "by_class": dict(Counter(a["class_name"] or "<unknown>" for a in actors)),
    }


def summarize_objects() -> dict:
    objs = list_objects()
    return {
        "total": len(objs),
        "total_bytes": sum(o["size"] or 0 for o in objs),
        "ready": sum(1 for o in objs if o["ready"]),
        "pinned": sum(1 for o in objs if o["pins"]),
        "spilled_bytes": sum(
            o["size"] or 0 for o in objs if o.get("where") == "spilled"
        ),
    }


def summary() -> dict:
    """One-call cluster overview (CLI: ``python -m ray_tpu summary``)."""
    return {
        "nodes": list_nodes(),
        "tasks": summarize_tasks(),
        "actors": summarize_actors(),
        "objects": summarize_objects(),
    }


# ---------------------------------------------------------------------------
# timeline (reference: `ray timeline` -> chrome://tracing)
# ---------------------------------------------------------------------------


def timeline(path: Optional[str] = None) -> list[dict]:
    """Chrome-trace 'complete' events (ph=X) from RUNNING->FINISHED/FAILED
    pairs in the task-event feed. Load the file via chrome://tracing or
    https://ui.perfetto.dev."""
    events = get_task_events()
    open_ts: dict[str, dict] = {}
    trace: list[dict] = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            open_ts[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in open_ts:
            start = open_ts.pop(tid)
            args = {"state": ev["state"], "task_id": tid}
            rid = ev.get("request_id") or start.get("request_id")
            if rid:
                # one lane per request: tracing.export_chrome_trace mirrors
                # entries carrying a request_id into the "requests" group
                args["request_id"] = rid
            trace.append(
                {
                    "name": ev.get("name") or tid[:8],
                    "cat": ev.get("kind") or "task",
                    "ph": "X",
                    "ts": start["time"] * 1e6,
                    "dur": max(0.0, (ev["time"] - start["time"]) * 1e6),
                    "pid": "ray_tpu",
                    "tid": tid[:8],
                    "args": args,
                }
            )
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
