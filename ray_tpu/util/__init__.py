"""Utility APIs (reference: ``python/ray/util/``)."""

from ray_tpu.util.placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def __getattr__(name):
    import importlib

    for mod in ("actor_pool", "queue", "metrics", "state"):
        try:
            m = importlib.import_module(f"ray_tpu.util.{mod}")
        except ImportError:
            continue
        if hasattr(m, name):
            return getattr(m, name)
        if mod == name:
            return m
    raise AttributeError(name)
