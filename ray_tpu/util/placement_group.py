"""Placement groups (reference: ``python/ray/util/placement_group.py:146`` +
GCS placement group manager / bundle scheduling policies).

On TPU pods these are the slice primitive: ``placement_group([{"TPU": 4}] *
n_hosts, strategy="STRICT_SPREAD")`` reserves one bundle per host of a slice,
and STRICT_PACK keeps a whole group inside one ICI domain.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.runtime import ObjectRef, get_ctx

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes, bundles: list[dict]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self) -> ObjectRef:
        """An ObjectRef that resolves when all bundles are reserved
        (reference: ``PlacementGroup.ready()``)."""
        import threading

        from ray_tpu._private import serialization as ser
        from ray_tpu._private.ids import ObjectID

        ctx = get_ctx()
        pg_id = self.id
        obj_id = ObjectID.for_put().binary()
        ctx.call("add_ref", obj_id=obj_id)

        def fill():
            ctx.call("pg_ready", pg_id=pg_id, timeout=None)
            sv = ser.serialize(True)
            if hasattr(ctx, "head"):
                ctx.head.put_at(obj_id, sv)
            else:
                ctx.call("put", obj_id=obj_id, small=sv.to_bytes(), shm=None)

        threading.Thread(target=fill, daemon=True).start()
        return ObjectRef(obj_id, owned=True)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        return get_ctx().call("pg_ready", pg_id=self.id, timeout=timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: list[dict],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid strategy {strategy!r}; must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError("each bundle must be a non-empty resource dict")
        if any(v < 0 for v in b.values()):
            raise ValueError("bundle resources must be >= 0")
    pg_id = get_ctx().call("create_pg", bundles=bundles, strategy=strategy, name=name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    get_ctx().call("remove_pg", pg_id=pg.id)


def placement_group_table() -> list[dict]:
    # round-1: summary via nodes(); detailed table in the state API
    return []
