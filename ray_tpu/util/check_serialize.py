"""Serializability inspection.

Counterpart of the reference's ``ray.util.check_serialize
.inspect_serializability`` — walks a failing object's closure/attributes to
point at the exact leaf that cloudpickle chokes on, instead of surfacing one
opaque ``TypeError`` from deep inside a task submission.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, List, Optional

# AST-level table shared with raylint's RL003 (unserializable closure
# capture): dotted constructor name -> why instances of it cannot cross a
# task boundary. Kept here, next to the runtime-side inspector, so the two
# views of "what cloudpickle chokes on" stay in one place. This module must
# stay import-side-effect free (the serializer import is lazy, below) so the
# linter can use it without dragging in the runtime.
KNOWN_UNSERIALIZABLE_CALLS: dict[str, str] = {
    "threading.Lock": "holds OS lock state",
    "threading.RLock": "holds OS lock state",
    "threading.Condition": "wraps an OS lock",
    "threading.Event": "wraps an OS lock",
    "threading.Semaphore": "wraps an OS lock",
    "threading.BoundedSemaphore": "wraps an OS lock",
    "threading.local": "thread-local storage",
    "_thread.allocate_lock": "holds OS lock state",
    "multiprocessing.Lock": "holds OS lock state",
    "multiprocessing.Queue": "backed by an OS pipe",
    "queue.Queue": "contains locks/conditions",
    "queue.LifoQueue": "contains locks/conditions",
    "queue.PriorityQueue": "contains locks/conditions",
    "socket.socket": "OS socket handle",
    "socket.create_connection": "OS socket handle",
    "open": "open file handle",
    "io.open": "open file handle",
    "subprocess.Popen": "live child process",
    "sqlite3.connect": "database connection handle",
    "mmap.mmap": "memory-mapped OS handle",
    "concurrent.futures.ThreadPoolExecutor": "live thread pool",
    "concurrent.futures.ProcessPoolExecutor": "live process pool",
}


@dataclasses.dataclass
class FailureTuple:
    """One unserializable leaf. ``obj`` is the failing object, ``name`` its
    best-known label, ``parent`` the container it was reached from."""

    obj: Any
    name: str
    parent: Any

    def __repr__(self):
        return f"FailTuple({self.name} [obj={self.obj!r}, parent={self.parent!r}])"


def _try_pickle(obj: Any) -> Optional[Exception]:
    # Lazy so that importing this module (e.g. from the linter) never pulls
    # in the runtime serializer and its cloudpickle dependency.
    from ray_tpu._private import serialization as ser

    try:
        ser.dumps(obj)
        return None
    except Exception as e:  # noqa: BLE001 - any serializer failure counts
        return e


def inspect_serializability(
    obj: Any, name: Optional[str] = None, depth: int = 3, _failures=None, _seen=None
) -> tuple[bool, List[FailureTuple]]:
    """Check whether ``obj`` cloudpickles; on failure, descend into closures,
    attributes and containers to locate root causes.

    Returns ``(serializable, failures)`` where ``failures`` holds the deepest
    offending leaves found (the reference prints a tree; we return the data
    and let the caller format it).
    """
    name = name or getattr(obj, "__qualname__", None) or repr(obj)[:60]
    failures: List[FailureTuple] = [] if _failures is None else _failures
    seen = set() if _seen is None else _seen

    err = _try_pickle(obj)
    if err is None:
        return True, failures
    if id(obj) in seen or depth < 0:
        return False, failures
    seen.add(id(obj))

    found_deeper = False
    children: list[tuple[str, Any]] = []
    if inspect.isfunction(obj) or inspect.ismethod(obj):
        closure = inspect.getclosurevars(obj)
        children += [(f"nonlocal {k}", v) for k, v in closure.nonlocals.items()]
        children += [(f"global {k}", v) for k, v in closure.globals.items()]
    elif isinstance(obj, dict):
        children += [(str(k), v) for k, v in obj.items()]
    elif isinstance(obj, (list, tuple, set)):
        children += [(f"[{i}]", v) for i, v in enumerate(obj)]
    elif hasattr(obj, "__dict__") and not inspect.isclass(obj):
        children += list(vars(obj).items())

    for child_name, child in children:
        if _try_pickle(child) is not None:
            found_deeper = True
            ok, _ = inspect_serializability(
                child, name=child_name, depth=depth - 1, _failures=failures, _seen=seen
            )

    if not found_deeper and not any(f.obj is obj for f in failures):
        failures.append(FailureTuple(obj=obj, name=name, parent=None))
    return False, failures
