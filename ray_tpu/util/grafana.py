"""Grafana dashboard JSON generator.

Reference: ``dashboard/modules/metrics/grafana_dashboard_factory.py`` — the
reference generates its default Grafana boards (cluster utilization, task
states, node metrics) from panel templates at dashboard startup. Here the
generator builds one importable dashboard from (a) the core runtime series
every cluster exports once ``start_core_metrics()`` runs (the dashboard
server starts it) and (b) whatever user metrics are currently registered in
``ray_tpu.util.metrics``. Output follows the modern schema (schemaVersion
39, timeseries panels) and imports cleanly into Grafana 9/10/11.

Usage::

    python -m ray_tpu grafana > ray_tpu_dashboard.json
    # or REST: GET /api/grafana on a running dashboard
"""

from __future__ import annotations

import json
from typing import Optional

# (title, promql expr, unit, description) — core series exported by
# util.metrics.start_core_metrics(); names carry the ray_tpu_ prefix that
# prometheus_text() adds.
_CORE_PANELS = [
    ("Tasks by state", 'ray_tpu_core_tasks{{state=~".+"}}', "short",
     "Cluster task counts per scheduler state (PENDING/RUNNING/...)."),
    ("Actors by state", 'ray_tpu_core_actors{{state=~".+"}}', "short",
     "Actor FSM states (PENDING/ALIVE/RESTARTING/DEAD)."),
    ("Alive nodes", "ray_tpu_core_nodes", "short",
     "Nodes registered and alive in the cluster."),
    ("Logical resource utilization", 'ray_tpu_core_resource_used{{resource=~".+"}}', "short",
     "Used amount per logical resource (CPU/TPU/custom)."),
    ("Logical resource capacity", 'ray_tpu_core_resource_total{{resource=~".+"}}', "short",
     "Registered total per logical resource — pair with utilization to see headroom."),
    ("Object store objects", "ray_tpu_core_objects", "short",
     "Objects tracked by the head directory."),
    ("Object store bytes", "ray_tpu_core_object_bytes", "bytes",
     "Total bytes of tracked objects (inline + shm)."),
    ("Spilled bytes", "ray_tpu_core_spilled_bytes", "bytes",
     "Bytes currently spilled to disk."),
]

# LLM serving row (engine metrics export from replica processes, so the
# registry-driven loop below can't discover them from the dashboard
# process — they get a static row instead; names: llm/engine.py).
_LLM_PANELS = [
    ("LLM tokens/s", "rate(ray_tpu_llm_generated_tokens[1m])", "short",
     "Engine-wide generation throughput."),
    # running and waiting are separate panels: both series are untagged,
    # so a PromQL `a or b` would drop `b` whenever `a` exists
    ("LLM running requests", "ray_tpu_llm_running_requests", "short",
     "Requests currently holding decode slots."),
    ("LLM waiting requests", "ray_tpu_llm_waiting_requests", "short",
     "Requests queued for admission (upscale pressure)."),
    ("KV block utilization", "ray_tpu_llm_kv_block_utilization", "percentunit",
     "Fraction of paged-KV blocks in use (preemption pressure above the threshold)."),
    ("TTFT p99",
     'histogram_quantile(0.99, rate(ray_tpu_llm_time_to_first_token_s_bucket[5m]))',
     "s", "Time to first token (SLO latency; obs top shows the live snapshot)."),
    ("Inter-token latency p99",
     'histogram_quantile(0.99, rate(ray_tpu_llm_inter_token_latency_s_bucket[5m]))',
     "s", "Gap between consecutive streamed tokens."),
    ("Speculative acceptance rate", "ray_tpu_llm_spec_acceptance_rate", "percentunit",
     "Accepted/proposed draft tokens of the last verify window."),
]


def _prefix_panels() -> list:
    """Cross-request prefix-cache row, DERIVED from the metric family
    ``llm.prefix_cache`` exports (``prefix_cache.METRIC_NAMES`` is the
    contract; tests cross-check this row against it so the dashboard
    can't silently drift from the code): hit rate, hit/miss token rates,
    eviction pressure, resident tree size."""
    return [
        ("Prefix cache hit rate", "ray_tpu_llm_prefix_cache_hit_rate",
         "percentunit",
         "Lifetime hit_tokens / (hit+miss) — prompt tokens served from "
         "cached KV instead of prefill."),
        ("Prefix hit tokens/s",
         "rate(ray_tpu_llm_prefix_cache_hit_tokens[1m])", "short",
         "Prompt tokens/s whose prefill was skipped via cached blocks."),
        ("Prefix miss tokens/s",
         "rate(ray_tpu_llm_prefix_cache_miss_tokens[1m])", "short",
         "Prompt tokens/s actually prefilled (compare llm_prefill_tokens)."),
        ("Prefix evictions/s",
         "rate(ray_tpu_llm_prefix_cache_evicted_blocks[1m])", "short",
         "Cached blocks reclaimed under KV pressure — sustained rate "
         "means the tree is thrashing; grow the pool."),
        ("Prefix cache blocks", "ray_tpu_llm_prefix_cache_blocks", "short",
         "KV blocks resident in the radix tree."),
    ]


def _profiling_panels() -> list:
    """Continuous-profiling row, DERIVED from the profiling-plane metric
    families (``util.waterfall.METRIC_NAMES``, ``util.device_prof
    .METRIC_NAMES`` and the engine's ``llm_hbm_*`` ledger gauges — tests
    cross-check this row against those registries): task-hop waterfall
    percentiles per phase, device-step time per jit site, runtime
    retraces, and the HBM ledger the tiered-KV spill decision reads."""
    return [
        ("Submit window size p50",
         'histogram_quantile(0.5, rate(ray_tpu_core_submit_batch_size_bucket[5m]))',
         "short",
         "Tasks per pipelined submit window received by the head "
         "(core_submit_batch_size) — 1 means the plane is running "
         "un-batched sync round trips; bursts should push this toward "
         "core_submit_batch_max."),
        ("Submit window size p99",
         'histogram_quantile(0.99, rate(ray_tpu_core_submit_batch_size_bucket[5m]))',
         "short",
         "Tail submit-window size — how big bursts actually get before "
         "the core_submit_batch_max cap or a blocking RPC flushes them."),
        ("Reply batch size p50",
         'histogram_quantile(0.5, rate(ray_tpu_core_reply_batch_size_bucket[5m]))',
         "short",
         "Completions per coalesced worker reply message "
         "(core_reply_batch_size); pair with core_submit_credits on the "
         "submitter to spot window-credit stalls."),
        ("Reply batch size p99",
         'histogram_quantile(0.99, rate(ray_tpu_core_reply_batch_size_bucket[5m]))',
         "short",
         "Tail reply-batch size under load (the off-path flusher drains "
         "whatever accumulated, capped at core_reply_batch_max)."),
        ("Task-hop p99 by phase",
         'histogram_quantile(0.99, rate(ray_tpu_core_task_phase_s_bucket{{phase=~".+"}}[5m]))',
         "s",
         "Per-hop task-plane latency (submit/serialize/socket_write/"
         "head_dispatch/worker_deserialize/exec/reply/total) folded on "
         "the head from sampled tasks' waterfall stamps."),
        ("Waterfalls folded/s",
         "rate(ray_tpu_core_task_waterfalls[1m])", "short",
         "Complete 8-stamp records folded per second (sampled tasks "
         "only; core_task_waterfall_incomplete counts partial replies)."),
        ("Device step p99 by site",
         'histogram_quantile(0.99, rate(ray_tpu_device_step_seconds_bucket{{site=~".+"}}[5m]))',
         "s",
         "Wall time per jitted entry-point call (decode/prefill/verify/"
         "fork/train_step), compiles included."),
        ("Jit retraces/s",
         'rate(ray_tpu_device_retraces[5m])', "short",
         "Sites recompiling AFTER warmup (RL014's runtime twin) — any "
         "sustained rate fires the retrace-storm SLO rule."),
        # one panel per ledger gauge. The pool-wide series is untagged, so
        # a PromQL `a or b` would collapse to `a` (same pitfall the
        # running/waiting panels document above); under a tensor-parallel
        # engine (EngineConfig(tp>1)) every gauge ALSO publishes one
        # series per mesh device tagged `device="<id>"` — a plain
        # metric-name expr renders them all as separate legend entries,
        # so these panels need no per-tp variant
        ("HBM params bytes", "ray_tpu_llm_hbm_params_bytes", "bytes",
         "Device bytes held by model params (per-device series under "
         "tp>1 exceed the even split: replicated leaves are a full copy "
         "each)."),
        ("HBM seq-owned KV bytes", "ray_tpu_llm_hbm_kv_seq_bytes", "bytes",
         "KV blocks owned by ≥1 live sequence × block bytes."),
        ("HBM cache-resident KV bytes", "ray_tpu_llm_hbm_kv_cache_bytes",
         "bytes",
         "Prefix-cache-ONLY residents — what a host-RAM tier would "
         "reclaim (the tiered-KV spill signal)."),
        ("HBM free KV bytes", "ray_tpu_llm_hbm_kv_free_bytes", "bytes",
         "Free-list blocks × block bytes."),
        ("HBM drafter bytes", "ray_tpu_llm_hbm_drafter_bytes", "bytes",
         "Speculative drafter params (0 for the n-gram drafter)."),
        ("KV pool footprint", "ray_tpu_llm_hbm_kv_pool_bytes", "bytes",
         "Total device bytes of the paged-KV pool arrays (fixed at "
         "engine start; per-device series under tp>1 are exactly 1/tp — "
         "the head axis is sharded)."),
    ]


def _data_plane_panels() -> list:
    """Zero-copy data-plane row (ISSUE 18), DERIVED from the object-plane
    metric families (``_private.runtime.METRIC_NAMES`` counters + the
    head's locality gauge): shm write/read throughput, where reads were
    served from, and how often the scheduler moved tasks to their data."""
    return [
        ("Shm put throughput", "rate(ray_tpu_core_shm_put_bytes[1m])", "Bps",
         "Serialized bytes/s producers wrote straight into shared memory "
         "(core_shm_put_bytes) — these bytes ship as locators, never as "
         "control-socket payload."),
        ("Shm get throughput", "rate(ray_tpu_core_shm_get_bytes[1m])", "Bps",
         "Serialized bytes/s consumers read back out of shared-memory "
         "maps (core_shm_get_bytes)."),
        ("Local hits vs remote pulls",
         "rate(ray_tpu_core_data_local_hits[1m])", "short",
         "Shm reads served zero-copy from a same-host map "
         "(core_data_local_hits); plot ray_tpu_core_data_remote_pulls "
         "beside it — a rising remote share means tasks are landing away "
         "from their data."),
        ("Remote pulls/s",
         "rate(ray_tpu_core_data_remote_pulls[1m])", "short",
         "Shm reads that crossed hosts via the p2p data plane "
         "(core_data_remote_pulls) — each one is a full payload copy the "
         "locality scheduler tries to avoid."),
        ("Scheduler locality hit rate",
         "ray_tpu_core_sched_locality_hit_rate", "percentunit",
         "Fraction of ref-arg task placements that landed on a node "
         "already holding the args' shm bytes "
         "(core_sched_locality_hit_rate); sustained low values mean "
         "byte-holding nodes are capacity-starved."),
    ]


def _objects_panels() -> list:
    """Object-ledger row (ISSUE 19), DERIVED from the head's object-plane
    metric family (``_private.head.METRIC_NAMES`` — tests cross-check this
    row against the registry): per-node arena residency, pin pressure,
    spill churn, object lifetimes, and the standing leak-audit verdict."""
    return [
        ("Arena used by node",
         'ray_tpu_core_arena_used_bytes{{node=~".+"}}', "bytes",
         "Bytes allocated in each node's native object arena "
         "(core_arena_used_bytes) — plot against "
         "ray_tpu_core_arena_capacity_bytes; the worst ratio drives the "
         "arena-pressure SLO rule."),
        ("Arena pinned by node",
         'ray_tpu_core_arena_pinned_bytes{{node=~".+"}}', "bytes",
         "Arena bytes held by live reader pins per node "
         "(core_arena_pinned_bytes) — pinned bytes can't be recycled; "
         "obs objects --audit flags pins older than the read lease."),
        ("Arena occupancy (worst node)",
         "ray_tpu_core_arena_occupancy", "percentunit",
         "Worst-node used/capacity ratio (core_arena_occupancy) — the "
         "arena-pressure SLO gauge."),
        ("Spilled bytes by node",
         'ray_tpu_core_spill_bytes{{node=~".+"}}', "bytes",
         "Directory objects currently spilled to each node's disk "
         "(core_spill_bytes)."),
        ("Object spills/s",
         "rate(ray_tpu_core_object_spills[1m])", "short",
         "Directory objects spilled under arena pressure "
         "(core_object_spills) — any sustained rate fires the spill-burn "
         "SLO rule."),
        ("Object lifetime p99",
         'histogram_quantile(0.99, rate(ray_tpu_core_object_age_s_bucket[5m]))',
         "s",
         "Object age at free/evict (core_object_age_s) — a growing tail "
         "means refs are outliving their usefulness and holding arena "
         "bytes."),
        ("Object-plane leaks",
         "ray_tpu_core_object_leaks", "short",
         "Findings of the last leak audit (core_object_leaks; obs objects "
         "--audit / rpc_object_audit) — anything non-zero deserves a "
         "look: orphaned arena bytes, stale pins, dangling locators, or "
         "orphaned spill files."),
    ]


def _phases_panels() -> list:
    """Request-phases row (ISSUE 20), DERIVED from the phase registry
    (``util.phases.PHASES`` — tests cross-check this row against it):
    where a served request's milliseconds go, per phase. Assembly-only
    phases (computed by ``obs attribute`` from anchors, never exported
    as series) are skipped — a panel over a never-emitted series would
    be permanently empty."""
    from ray_tpu.util.phases import PHASES

    m = "ray_tpu_llm_request_phase_s"
    panels = [
        ("Request phase p99 (by phase)",
         f"histogram_quantile(0.99, sum by (le, phase) "
         f"(rate({m}_bucket[5m])))", "s",
         "p99 seconds per phase of the request lifecycle "
         "(llm_request_phase_s) — the fleet view of `obs attribute`: "
         "whichever line dominates owns the latency budget."),
        ("Request phase share (mean s/req)",
         f"sum by (phase) (rate({m}_sum[5m])) / ignoring(phase) "
         f"group_left sum(rate({m}_count[5m]))", "s",
         "Mean seconds each phase contributes per request — the stacked "
         "decomposition of end-to-end latency."),
    ]
    for name, owner, edges in PHASES:
        if owner == "assembly":
            continue  # no series: derived at attribution time
        panels.append((
            f"Phase {name} p99",
            f'histogram_quantile(0.99, rate({m}_bucket{{phase="{name}"}}'
            f"[5m]))", "s",
            f"{edges} (owner: {owner}).",
        ))
    return panels


def _slo_panels() -> list:
    """SLO / burn-rate row DERIVED from ``util.slo.default_rules()`` — the
    panels interpolate the same threshold/objective/window the head's alert
    engine evaluates (all env-tunable), so Grafana and ``obs alerts`` agree
    on what 'burning' means even after an operator retunes the rules."""
    from ray_tpu.util.slo import default_rules

    panels = []
    for rule in default_rules():
        budget = max(1e-9, 1.0 - rule.objective)
        window = f"[{max(int(rule.fast_window_s), 15)}s]"
        if rule.kind == "histogram_burn":
            m = f"ray_tpu_{rule.metric}"
            # the rule's series filter (e.g. phase="queue") rides both the
            # bucket and count selectors, matching _tags_match at eval time
            tagsel = "".join(
                f', {k}="{v}"' for k, v in (rule.tags or {}).items()
            )
            csel = "{" + tagsel[2:] + "}" if tagsel else ""
            expr = (
                f'(1 - (rate({m}_bucket{{le="{rule.threshold:g}"{tagsel}}}'
                f"{window}) "
                f"/ rate({m}_count{csel}{window}))) / {budget:g}"
            )
            title = f"{rule.name} fast burn rate"
        elif rule.kind == "counter_burn":
            m = f"ray_tpu_{rule.metric}"
            sel = ",".join(
                f'{k}="{v}"' for k, v in (rule.bad_tags or {}).items()
            )
            expr = (
                f"(sum(rate({m}{{{sel}}}{window})) "
                f"/ sum(rate({m}{window}))) / {budget:g}"
            )
            title = f"{rule.name} burn rate"
        else:  # gauge_threshold: show the gauge against its bound
            expr = f"ray_tpu_{rule.metric}"
            title = f"{rule.name} (fires ≥ {rule.threshold:g} for {rule.for_s:g}s)"
        panels.append((title, expr, "short", rule.description or rule.name))
    panels += [
        ("Serve requests/s",
         "sum(rate(ray_tpu_serve_requests[1m]))", "short",
         "Proxied HTTP request throughput across status classes."),
        ("Dropped spans/s",
         "rate(ray_tpu_tracing_dropped_spans[5m])", "short",
         "Spans evicted by the per-process retention cap "
         "(RAY_TPU_TRACE_MAX_SPANS) — sustained drops mean raise the cap "
         "or lower RAY_TPU_TRACE_SAMPLE."),
    ]
    return panels

# names the static LLM/SLO rows already cover — the dynamic user-metric
# loop skips them to avoid duplicate panels when the engine runs in-process
_LLM_NAMES = {
    "llm_generated_tokens", "llm_running_requests", "llm_waiting_requests",
    "llm_kv_block_utilization", "llm_time_to_first_token_s",
    "llm_inter_token_latency_s", "llm_spec_acceptance_rate",
    "serve_requests", "tracing_dropped_spans", "llm_finished_requests",
    "llm_prefix_cache_hit_tokens", "llm_prefix_cache_miss_tokens",
    "llm_prefix_cache_evicted_blocks", "llm_prefix_cache_hit_rate",
    "llm_prefix_cache_blocks", "llm_prefill_tokens",
    # profiling row (core_task_* skips via the core_ prefix)
    "device_step_seconds", "device_retraces",
    "llm_hbm_params_bytes", "llm_hbm_kv_pool_bytes", "llm_hbm_kv_seq_bytes",
    "llm_hbm_kv_cache_bytes", "llm_hbm_kv_free_bytes",
    "llm_hbm_drafter_bytes",
    # request-phases row (_phases_panels)
    "llm_request_phase_s",
}


def _panel(panel_id: int, title: str, expr: str, unit: str, desc: str, y: int) -> dict:
    return {
        "id": panel_id,
        "type": "timeseries",
        "title": title,
        "description": desc,
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": 12 * (panel_id % 2), "y": y},
        "fieldConfig": {
            "defaults": {
                "unit": unit,
                "custom": {"drawStyle": "line", "lineWidth": 1, "fillOpacity": 12},
            },
            "overrides": [],
        },
        "targets": [
            {
                "expr": expr.replace("{{", "{").replace("}}", "}"),
                "legendFormat": "__auto",
                "refId": "A",
                "datasource": {"type": "prometheus", "uid": "${datasource}"},
            }
        ],
    }


def dashboard_json(extra_metric_names: Optional[list[str]] = None) -> dict:
    """Build the dashboard dict. ``extra_metric_names`` defaults to every
    metric currently registered in this process's registry."""
    from ray_tpu.util import metrics as um

    kinds: dict[str, str] = {}
    if extra_metric_names is None:
        with um._registry_lock:
            kinds = {
                m.name: m.kind
                for m in um._registry
                if not m.name.startswith("core_")
            }
        names = sorted(kinds)
    else:
        names = list(extra_metric_names)
    panels = []
    y = 0
    pid = 0
    for title, expr, unit, desc in (_CORE_PANELS + _LLM_PANELS
                                    + _prefix_panels() + _profiling_panels()
                                    + _data_plane_panels() + _objects_panels()
                                    + _phases_panels() + _slo_panels()):
        panels.append(_panel(pid, title, expr, unit, desc, y))
        pid += 1
        if pid % 2 == 0:
            y += 8
    for name in names:
        if name in _LLM_NAMES:
            continue
        if kinds.get(name) == "histogram":
            # the exporter emits _bucket/_sum/_count for histograms, never
            # the bare name — a bare-name panel would be permanently empty
            expr = (
                f"histogram_quantile(0.99, "
                f"rate(ray_tpu_{name}_bucket[5m]))"
            )
            title = f"{name} (p99)"
        else:
            expr = f"ray_tpu_{name}"
            title = name
        panels.append(
            _panel(pid, title, expr, "short", f"User metric {name!r}.", y)
        )
        pid += 1
        if pid % 2 == 0:
            y += 8
    return {
        "title": "ray_tpu",
        "uid": "ray-tpu-core",
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "schemaVersion": 39,
        "version": 1,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {
            "list": [
                {
                    "name": "datasource",
                    "type": "datasource",
                    "query": "prometheus",
                    "current": {},
                }
            ]
        },
        "panels": panels,
        "annotations": {"list": []},
        "editable": True,
    }


def write_dashboard(path: str, **kw) -> str:
    with open(path, "w") as f:
        json.dump(dashboard_json(**kw), f, indent=2)
    return path
