"""Distributed FIFO queue backed by an actor.

Reference: ``python/ray/util/queue.py`` (Queue over a ``_QueueActor`` with
put/get/qsize/empty/full + *_nowait + batch variants). Any process holding
the Queue object (it pickles by actor handle) shares the same FIFO.
"""

from __future__ import annotations

import queue as _pyqueue
import time
from typing import Any, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: _pyqueue.Queue = _pyqueue.Queue(maxsize=maxsize)

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()

    def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            self._q.put(item, block=timeout != 0, timeout=timeout or None)
            return True
        except _pyqueue.Full:
            return False

    def get(self, timeout: Optional[float] = None):
        try:
            return (True, self._q.get(block=timeout != 0, timeout=timeout or None))
        except _pyqueue.Empty:
            return (False, None)

    def put_batch(self, items: list, timeout: Optional[float] = None) -> bool:
        # atomic: reject the WHOLE batch if it can't fit (a partial insert
        # would duplicate items when the caller retries after Full)
        maxsize = self._q.maxsize
        if maxsize > 0 and self._q.qsize() + len(items) > maxsize:
            return False
        for item in items:
            self._q.put(item)
        return True

    def get_batch(self, max_items: int):
        out = []
        while len(out) < max_items:
            ok, item = self.get(timeout=0)
            if not ok:
                break
            out.append(item)
        return out


class Queue:
    """``Queue(maxsize=0)`` — 0 means unbounded.

    Blocking semantics run inside the actor (``max_concurrency`` keeps
    control calls live while a ``get`` blocks), so producers/consumers in
    different processes coordinate exactly like ``queue.Queue`` threads.
    """

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        cls = ray_tpu.remote(_QueueActor)
        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 8)
        self.actor = cls.options(**opts).remote(maxsize)
        self.maxsize = maxsize

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    # blocking semantics loop CLIENT-side over short actor-side waits — an
    # unbounded block inside the actor would pin one of its threads and can
    # wedge the pool (getters starving putters)
    _SLICE = 0.2

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        # ship the payload ONCE: retry slices re-send only a tiny ObjectRef,
        # not the item (a blocked 100MB put must not re-serialize per slice)
        ref = ray_tpu.put(item)
        while True:
            slice_t = 0 if not block else self._SLICE
            if deadline is not None:
                slice_t = max(0, min(slice_t, deadline - time.monotonic()))
            ok = ray_tpu.get(self.actor.put.remote(ref, slice_t))
            if ok:
                return
            if not block or (deadline is not None and time.monotonic() >= deadline):
                raise Full("ray_tpu.util.queue.Queue is full")

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def put_nowait_batch(self, items: list):
        if not ray_tpu.get(self.actor.put_batch.remote(list(items), 0)):
            raise Full("ray_tpu.util.queue.Queue is full")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            slice_t = 0 if not block else self._SLICE
            if deadline is not None:
                slice_t = max(0, min(slice_t, deadline - time.monotonic()))
            ok, item = ray_tpu.get(self.actor.get.remote(slice_t))
            if ok:
                return self._resolve(item)
            if not block or (deadline is not None and time.monotonic() >= deadline):
                raise Empty("ray_tpu.util.queue.Queue is empty")

    @staticmethod
    def _resolve(item):
        from ray_tpu._private.runtime import ObjectRef

        return ray_tpu.get(item) if isinstance(item, ObjectRef) else item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def get_nowait_batch(self, max_items: int) -> list:
        return [
            self._resolve(i)
            for i in ray_tpu.get(self.actor.get_batch.remote(max_items))
        ]

    def shutdown(self):
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass
