"""Cluster pubsub: named channels pushed from the head.

Counterpart of the reference's pubsub layer (``src/ray/pubsub/`` —
long-poll publisher/subscriber channels carrying GCS actor/job/node
updates). TPU-first shape: the head pushes ``("pub", channel, payload)``
frames down each subscriber's existing control socket (no long-poll
round-trips), and in-process drivers subscribe with a plain callback.

Built-in channels published by the head:

* ``"nodes"`` — ``{"event": "added"|"removed", "node_id": hex, ...}``
* ``"actors"`` — ``{"event": "ALIVE"|"RESTARTING"|"DEAD", "actor_id": hex,
  "name": str|None}``

Any other channel name is application-defined: ``publish(channel, msg)``
fans out to every subscriber in the cluster.
"""

from __future__ import annotations

import queue
from typing import Any, Optional

from ray_tpu._private.runtime import get_ctx


class Subscriber:
    """Iterator/queue view of one channel subscription."""

    def __init__(self, channel: str, maxsize: int = 10_000):
        self.channel = channel
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._closed = False
        get_ctx().pub_register(channel, self._on_msg)

    def _on_msg(self, _channel: str, payload) -> None:
        try:
            self._q.put_nowait(payload)
        except queue.Full:
            pass  # slow subscriber: drop (reference: pubsub buffer caps)

    def get(self, timeout: Optional[float] = None):
        """Next message, or raise ``queue.Empty`` after ``timeout``."""
        return self._q.get(timeout=timeout)

    def poll(self) -> list:
        """Drain everything currently buffered without blocking."""
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                get_ctx().pub_unregister(self.channel, self._on_msg)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        while not self._closed:
            yield self.get()


def subscribe(channel: str) -> Subscriber:
    return Subscriber(channel)


def publish(channel: str, message: Any) -> None:
    """Deliver ``message`` to every current subscriber of ``channel``."""
    get_ctx().call("publish", channel=channel, payload=message)
