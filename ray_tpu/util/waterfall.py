"""Task-hop waterfall: where a task's microseconds go, hop by hop.

The core plane is IPC-bound (BENCH_r06 showed telemetry absent from the
sync-task profile), but "IPC-bound" is not actionable — batched RPCs and
submission pipelining need to know WHICH hop owns the time.  This module
stamps monotonic phase timestamps onto task specs and replies and folds
completed records into per-phase histograms on the head:

    submit → serialize → socket_write → head_dispatch →
    worker_deserialize → exec_start → exec_end → reply_recv

Eight stamps give the eight-phase breakdown ``obs waterfall`` renders
(seven consecutive legs plus ``total``):

| phase | measures |
|---|---|
| ``submit``             | argument serialization (``serialize_args``) |
| ``serialize``          | spec build + submit-RPC entry |
| ``socket_write``       | client→head transfer + head queue/schedule |
| ``head_dispatch``      | head→worker transfer + worker queue |
| ``worker_deserialize`` | function resolve + argument fetch/deserialize |
| ``exec``               | the task body itself |
| ``reply``              | result store + worker→head completion |
| ``total``              | submit → reply received |

Zero-cost contract (PR 11): stamps ride the SAMPLED trace path only.
``maybe_start`` returns a stamp list only for a sampled dict context —
unsampled tokens, lazy rootless contexts, and streaming tasks ship no
stamps and pay one ``type()`` check.  The emit path (``maybe_start`` /
``stamp``) is append-plus-clock: no locks, no allocation beyond the one
list per sampled task — ``tests/test_obs_hotpath.py`` extends the
index-backed zero-lock lint fixture over both functions.  All folding
cost (histogram observes, the recent-record ring) lives on the head at
reply time, off every submitter's and worker's path.

Clocks: stamps are ``time.time()`` so they compare across processes on
one host (workers share the head's clock).  A wall-clock step can
produce a negative leg; the fold clamps legs at zero rather than
discarding the record.

Batched legs (PR 14): the 7-phase contract survives submission
pipelining and reply coalescing — each task keeps its OWN stamp list,
and batching moves WHERE a stamp is taken, never whether.  A spec
buffered in a driver/worker submit outbox takes ``socket_write`` when
its batch is actually written (queue time charges the socket_write leg);
``head_dispatch`` covers the head outbox + coalesced ``run_task_batch``
write + the worker recv loop's receive-and-parse, with
``worker_deserialize`` stamped AT that receipt — so task #64 of a deep
batch charges its exec-queue wait to its own
worker_deserialize→exec_start leg, not to the head's hop; a completion
deferred into the worker's reply outbox charges the defer + batch write
to its ``reply`` leg.  Phases are never dropped for batched tasks, and
per-task stamps stay monotonic because every boundary is stamped at the
moment that task's bytes (or its batch's bytes) move.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

#: stamp names in spec/reply order — index i of a record's stamp list
PHASES = (
    "submit",
    "serialize",
    "socket_write",
    "head_dispatch",
    "worker_deserialize",
    "exec_start",
    "exec_end",
    "reply_recv",
)

#: the rendered breakdown: (leg name, start stamp index, end stamp index)
LEGS = (
    ("submit", 0, 1),
    ("serialize", 1, 2),
    ("socket_write", 2, 3),
    ("head_dispatch", 3, 4),
    ("worker_deserialize", 4, 5),
    ("exec", 5, 6),
    ("reply", 6, 7),
    ("total", 0, 7),
)

#: raylint RL012 registry — the per-leg histogram the head folds into
#: and the fold counters beside it
METRIC_NAMES = (
    "core_task_phase_s",
    "core_task_waterfalls",
    "core_task_waterfall_incomplete",
)

#: boundaries sized for per-hop microseconds on a local socket up through
#: real execution seconds (the default metrics boundaries start at 5ms —
#: every IPC leg would land in the first bucket)
_PHASE_BOUNDARIES = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 1.0, 5.0,
)

_METRICS = None
_METRICS_LOCK = threading.Lock()

#: per-leg tag dicts built once — fold() runs on the head at every reply
#: receipt, and 8 dict literals per fold showed up in the reply-leg p50
_LEG_TAGS = {name: {"phase": name} for name, _i, _j in LEGS}

# newest folded records, for chrome-trace nested slices (obs timeline)
# and obs waterfall --recent; bounded drop-oldest
_RECENT_CAP = 256
_recent: deque = deque(maxlen=_RECENT_CAP)
_folded = 0
_incomplete = 0

#: raylint RL017 — _recent is appended by whichever head thread folds a
#: reply and snapshot by summary() with list(); deque ops are GIL-atomic
#: (the RuntimeError retry in summary() handles the one observable race).
#: clear() is a tests-only reset, suppressed inline below.
LOCKFREE = ("_recent: atomic",)


def _metrics() -> dict:
    global _METRICS
    if _METRICS is not None:
        return _METRICS
    with _METRICS_LOCK:
        if _METRICS is not None:
            return _METRICS
        from ray_tpu.util.metrics import Counter, Histogram

        _METRICS = {
            "phase": Histogram(
                "core_task_phase_s",
                "per-hop task-plane latency (submit/serialize/socket_write/"
                "head_dispatch/worker_deserialize/exec/reply/total)",
                boundaries=_PHASE_BOUNDARIES,
                tag_keys=("phase",),
            ),
            "folded": Counter(
                "core_task_waterfalls",
                "complete 8-stamp waterfall records folded on the head",
            ),
            "incomplete": Counter(
                "core_task_waterfall_incomplete",
                "stamped tasks whose reply carried a partial stamp list "
                "(errors before exec, retries re-dispatched, streaming)",
            ),
        }
    return _METRICS


# ---------------------------------------------------------------------------
# emit path (submitter / head / worker) — must stay lock-free
# ---------------------------------------------------------------------------


def maybe_start(spec_ctx) -> Optional[list]:
    """The submit stamp, taken only when the spec ships a SAMPLED dict
    trace context.  Unsampled tokens / lazy roots / no context return
    None — the task pays one ``type()`` check and ships nothing."""
    if type(spec_ctx) is dict:
        return [time.time()]
    return None


def stamp(wf: list) -> None:
    """Append the next phase timestamp (clock read + list append)."""
    wf.append(time.time())


# ---------------------------------------------------------------------------
# fold path (head, at reply receipt) and query surface
# ---------------------------------------------------------------------------


def fold(wf: list, spec: Optional[dict] = None) -> bool:
    """Head-side: close a reply's stamp list with ``reply_recv``, observe
    every leg into the per-phase histogram, and keep the record for the
    timeline.  Only exact 7-stamp replies fold (an error before
    ``exec_start``, or a retry whose spec accumulated a second
    ``head_dispatch``, yields a partial list — counted, not folded).
    Returns True when the record folded."""
    global _folded, _incomplete
    m = _metrics()
    if len(wf) == len(PHASES):
        wf = list(wf)  # reply_recv already stamped at message receipt
    elif len(wf) != len(PHASES) - 1:
        _incomplete += 1
        m["incomplete"].inc()
        return False
    else:
        wf = list(wf)
        wf.append(time.time())
    legs = {}
    observe = m["phase"].observe
    for name, i, j in LEGS:
        dur = max(0.0, wf[j] - wf[i])  # clamp wall-clock steps
        legs[name] = dur
        observe(dur, tags=_LEG_TAGS[name])
    m["folded"].inc()
    _folded += 1
    rec = {"stamps": wf, "legs": legs}
    if spec is not None:
        rec["name"] = spec.get("name")
        rec["kind"] = spec.get("kind")
        tctx = spec.get("trace_ctx")
        if tctx is not None:
            rec["request_id"] = tctx.get("request_id")
    _recent.append(rec)
    return True


def summary(recent: int = 0) -> dict:
    """The head's folded view: per-leg percentile summaries (what ``obs
    waterfall`` / the ``obs top`` row render) plus, optionally, the
    newest ``recent`` raw records (what the chrome trace nests)."""
    m = _metrics()
    legs = {
        name: m["phase"].percentiles(
            qs=(0.5, 0.95, 0.99), tags={"phase": name}
        )
        for name, _i, _j in LEGS
    }
    out = {
        "folded": _folded,
        "incomplete": _incomplete,
        "phases": list(PHASES),
        "legs": legs,
    }
    if recent:
        try:
            rows = list(_recent)
        except RuntimeError:
            # a concurrent fold appended mid-iteration (deque iterators
            # refuse mutation); one retry sees the settled ring
            rows = list(_recent)
        out["recent"] = rows[-recent:]
    return out


def clear() -> None:
    """Test hook: drop the recent ring + fold counts (histograms are
    process-lifetime like every metric). A reset racing a live fold is
    advisory by contract — tests quiesce the plane first — hence the
    RL017 suppressions on the fold-counter stores."""
    global _folded, _incomplete
    _recent.clear()
    _folded = 0  # raylint: disable=RL017
    _incomplete = 0  # raylint: disable=RL017


def chrome_slices(records: list[dict]) -> list[dict]:
    """Nested chrome-trace slices for folded records (``obs timeline``):
    per record one ``total`` slice with the seven legs nested inside it,
    on a ``waterfall`` process group — request-tagged records lane by
    request id, the rest by task name."""
    out = []
    for rec in records:
        stamps = rec.get("stamps")
        if not stamps or len(stamps) != len(PHASES):
            continue
        rid = rec.get("request_id")
        tid = f"req:{rid}" if rid else (rec.get("name") or "task")
        base = {
            "cat": "waterfall",
            "ph": "X",
            "pid": "waterfall",
            "tid": tid,
        }
        args = {"kind": rec.get("kind"), "name": rec.get("name")}
        if rid:
            args["request_id"] = rid
        out.append(
            {
                **base,
                "name": rec.get("name") or "task",
                "ts": stamps[0] * 1e6,
                "dur": max(0.0, stamps[-1] - stamps[0]) * 1e6,
                "args": args,
            }
        )
        for name, i, j in LEGS:
            if name == "total":
                continue
            out.append(
                {
                    **base,
                    "name": name,
                    "ts": stamps[i] * 1e6,
                    "dur": max(0.0, stamps[j] - stamps[i]) * 1e6,
                }
            )
    return out
