"""OTLP-JSON export: spans, flight-recorder events, and metric series in
the OpenTelemetry wire schema.

The obs plane's native formats (chrome traces, JSONL rings, merged series
dicts) leave the cluster only as bespoke files; this module maps all three
onto OTLP/JSON so any OpenTelemetry-speaking backend (collector, Jaeger,
Tempo, Loki, Prometheus-via-collector) ingests them directly:

* spans          → ``resourceSpans``   (``scopeSpans[].spans[]``)
* recorder events → ``resourceLogs``   (``scopeLogs[].logRecords[]``)
* metric series  → ``resourceMetrics`` (``scopeMetrics[].metrics[]`` with
  ``sum``/``gauge``/``histogram`` data points)

Resource identity is (node, process): every span/event/series groups under
a resource carrying ``service.name``, ``process.pid``, and ``node.id``
attributes. A request id (16 hex chars) widens into the 32-hex OTLP
``traceId``, so one request's spans and log records correlate in any OTLP
backend exactly as they do in ``obs req``.

Sinks: the FILE sink always works (``export(path=...)``, one JSON document
holding all three sections — what ``obs export --otlp`` and the CI
postmortem artifact write); the HTTP sink is best-effort behind
``RAY_TPU_OTLP_ENDPOINT`` (each section POSTs to the standard
``/v1/traces`` / ``/v1/logs`` / ``/v1/metrics`` path, failures are
reported, never raised).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

_SCOPE = {"name": "ray_tpu.obs", "version": "1"}


# ---------------------------------------------------------------------------
# AnyValue / attribute encoding
# ---------------------------------------------------------------------------


def _any_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP JSON carries int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, str):
        return {"stringValue": v}
    try:
        return {"stringValue": json.dumps(v)}
    except TypeError:
        return {"stringValue": repr(v)}


def _attrs(d: dict) -> list[dict]:
    return [{"key": str(k), "value": _any_value(v)} for k, v in d.items()]


def _resource(pid: Any, node: Optional[str]) -> dict:
    attrs = {"service.name": "ray_tpu"}
    if pid is not None:
        attrs["process.pid"] = str(pid)
    if node:
        attrs["node.id"] = str(node)
    return {"attributes": _attrs(attrs)}


def _trace_id(request_id: Optional[str]) -> str:
    """32-hex OTLP traceId from a 16-hex request id (zero-padded left);
    spans with no request root get a hashed synthetic id."""
    if request_id:
        rid = "".join(c for c in str(request_id) if c in "0123456789abcdef")
        if rid:
            return rid[:32].rjust(32, "0")
    return hashlib.sha1(repr(request_id).encode()).hexdigest()[:32]


def _span_id(*parts: Any) -> str:
    return hashlib.sha1("|".join(repr(p) for p in parts).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# spans (chrome-trace "X" entries → OTLP spans)
# ---------------------------------------------------------------------------


def spans_to_otlp(spans: list[dict]) -> list[dict]:
    """Map chrome-trace complete events (the shape ``tracing.get_spans`` /
    ``state.timeline`` produce: ``ts``/``dur`` in µs, ``pid``/``tid``
    lanes, ``args``) to ``resourceSpans``."""
    by_res: dict[tuple, list] = {}
    for s in spans:
        if s.get("ph") not in (None, "X"):
            continue  # instant markers export as log records, not spans
        args = dict(s.get("args") or {})
        rid = args.get("request_id")
        ts_us = float(s.get("ts", 0.0))
        dur_us = float(s.get("dur", 0.0))
        span = {
            "traceId": _trace_id(rid),
            "spanId": _span_id(s.get("name"), ts_us, dur_us, s.get("pid"), s.get("tid")),
            "name": str(s.get("name", "span")),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(int(ts_us * 1000)),
            "endTimeUnixNano": str(int((ts_us + dur_us) * 1000)),
            "attributes": _attrs(args),
            "status": {},
        }
        key = (str(s.get("pid", "")), None)
        by_res.setdefault(key, []).append(span)
    return [
        {
            "resource": _resource(pid, node),
            "scopeSpans": [{"scope": _SCOPE, "spans": sp}],
        }
        for (pid, node), sp in sorted(
            by_res.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")
        )
    ]


# ---------------------------------------------------------------------------
# flight-recorder events → log records
# ---------------------------------------------------------------------------

_SEVERITY = (
    ("crash.", ("ERROR", 17)),
    ("alert.fire", ("WARN", 13)),
    ("ci.", ("WARN", 13)),
)


def _severity(etype: str) -> tuple[str, int]:
    for prefix, sev in _SEVERITY:
        if etype.startswith(prefix):
            return sev
    return ("INFO", 9)


def events_to_otlp(events: list[dict]) -> list[dict]:
    by_res: dict[tuple, list] = {}
    for e in events:
        etype = str(e.get("type", "event"))
        sev_text, sev_num = _severity(etype)
        rid = e.get("request_id")
        attrs = {
            k: v
            for k, v in e.items()
            if k not in ("ts", "type", "seq", "pid", "node") and v is not None
        }
        rec = {
            "timeUnixNano": str(int(float(e.get("ts", 0.0)) * 1e9)),
            "severityText": sev_text,
            "severityNumber": sev_num,
            "body": {"stringValue": etype},
            "attributes": _attrs(attrs),
        }
        if rid:
            rec["traceId"] = _trace_id(rid)
        key = (str(e.get("pid", "")), e.get("node"))
        by_res.setdefault(key, []).append(rec)
    return [
        {
            "resource": _resource(pid, node),
            "scopeLogs": [{"scope": _SCOPE, "logRecords": recs}],
        }
        for (pid, node), recs in sorted(
            by_res.items(), key=lambda kv: (kv[0][0], kv[0][1] or "")
        )
    ]


# ---------------------------------------------------------------------------
# metric series → resourceMetrics
# ---------------------------------------------------------------------------


def _dp_attrs(tagset: str) -> list[dict]:
    try:
        tags = json.loads(tagset) if tagset else {}
    except ValueError:
        tags = {}
    return _attrs(tags)


def series_to_otlp(merged: dict, help_text: Optional[dict] = None) -> list[dict]:
    """Merged cluster series (``metrics.collect_series`` shape) as ONE
    cluster resource of ``resourceMetrics``."""
    metrics_out = []
    for name in sorted(merged):
        ent = merged[name]
        kind = ent.get("kind", "counter")
        metric: dict = {
            "name": f"ray_tpu_{name}",
            "description": (help_text or {}).get(name, ""),
            "unit": "",
        }
        if kind == "histogram":
            bounds = [float(b) for b in (ent.get("boundaries") or ())]
            dps = []
            for tagset, points in ent.get("series", {}).items():
                for ts, vec in points:
                    if not isinstance(vec, (list, tuple)):
                        continue
                    buckets, s, count = vec[:-2], vec[-2], vec[-1]
                    dps.append(
                        {
                            "attributes": _dp_attrs(tagset),
                            "timeUnixNano": str(int(ts * 1e9)),
                            "count": str(int(count)),
                            "sum": float(s),
                            "bucketCounts": [str(int(c)) for c in buckets],
                            "explicitBounds": bounds,
                        }
                    )
            metric["histogram"] = {
                "dataPoints": dps,
                "aggregationTemporality": 2,  # CUMULATIVE
            }
        else:
            dps = [
                {
                    "attributes": _dp_attrs(tagset),
                    "timeUnixNano": str(int(ts * 1e9)),
                    "asDouble": float(v),
                }
                for tagset, points in ent.get("series", {}).items()
                for ts, v in points
                if isinstance(v, (int, float))
            ]
            if kind == "counter":
                metric["sum"] = {
                    "dataPoints": dps,
                    "aggregationTemporality": 2,
                    "isMonotonic": True,
                }
            else:
                metric["gauge"] = {"dataPoints": dps}
        metrics_out.append(metric)
    if not metrics_out:
        return []
    return [
        {
            "resource": _resource(None, None),
            "scopeMetrics": [{"scope": _SCOPE, "metrics": metrics_out}],
        }
    ]


# ---------------------------------------------------------------------------
# export + sinks
# ---------------------------------------------------------------------------


def export(
    path: Optional[str] = None,
    spans: Optional[list[dict]] = None,
    events: Optional[list[dict]] = None,
    series: Optional[dict] = None,
    help_text: Optional[dict] = None,
) -> dict:
    """Build the OTLP document (and write it when ``path`` is given).
    Returns ``{"resourceSpans": [...], "resourceLogs": [...],
    "resourceMetrics": [...]}`` — the three standard OTLP/JSON payload
    sections in one file."""
    doc = {
        "resourceSpans": spans_to_otlp(spans or []),
        "resourceLogs": events_to_otlp(events or []),
        "resourceMetrics": series_to_otlp(series or {}, help_text),
    }
    if path:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc


def otlp_endpoint() -> Optional[str]:
    return os.environ.get("RAY_TPU_OTLP_ENDPOINT") or None


def post(doc: dict, endpoint: Optional[str] = None, timeout: float = 5.0) -> dict:
    """Best-effort HTTP sink: POST each non-empty section to the standard
    OTLP path under ``endpoint`` (default ``RAY_TPU_OTLP_ENDPOINT``).
    Returns ``{path: status-or-error}``; never raises — export must not
    fail because a collector is down."""
    endpoint = endpoint or otlp_endpoint()
    out: dict[str, Any] = {}
    if not endpoint:
        return out
    import urllib.request

    sections = (
        ("/v1/traces", "resourceSpans"),
        ("/v1/logs", "resourceLogs"),
        ("/v1/metrics", "resourceMetrics"),
    )
    for urlpath, key in sections:
        body = doc.get(key) or []
        if not body:
            continue
        url = endpoint.rstrip("/") + urlpath
        try:
            req = urllib.request.Request(
                url,
                data=json.dumps({key: body}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                out[urlpath] = resp.status
        except Exception as e:  # collector down / bad endpoint: report, go on
            out[urlpath] = f"error: {e!r}"
    return out


def export_cluster(
    path: Optional[str] = None,
    events_dir: Optional[str] = None,
    offline: bool = False,
) -> tuple[dict, dict]:
    """Gather the cluster's spans + events + series and export them.
    ``offline=True`` skips every live drain and reads crash-flush JSONL
    only (CI postmortems, dead clusters). Returns ``(doc, counts)``."""
    from ray_tpu._private import events as _ev

    spans: list[dict] = []
    events: list[dict] = list(_ev.load_crash_files(events_dir))
    series: dict = {}
    help_text: dict = {}
    if not offline:
        from ray_tpu.util import metrics as _m
        from ray_tpu.util import state as _st
        from ray_tpu.util import tracing as _t

        try:
            spans = _st.timeline() + _t.collect_cluster_spans()
        except Exception:
            spans = _t.get_spans()
        try:
            seen = {(e.get("pid"), e.get("seq"), e.get("ts")) for e in events}
            for e in _ev.collect_cluster_events():
                if (e.get("pid"), e.get("seq"), e.get("ts")) not in seen:
                    events.append(e)
        except Exception:
            pass
        try:
            series = _m.collect_series()
            help_text = _m.collect().get("help", {})
        except Exception:
            series = {}
    doc = export(path, spans=spans, events=events, series=series,
                 help_text=help_text)
    counts = {
        "spans": sum(
            len(ss["spans"]) for r in doc["resourceSpans"] for ss in r["scopeSpans"]
        ),
        "events": sum(
            len(sl["logRecords"]) for r in doc["resourceLogs"] for sl in r["scopeLogs"]
        ),
        "metrics": sum(
            len(sm["metrics"]) for r in doc["resourceMetrics"]
            for sm in r["scopeMetrics"]
        ),
    }
    return doc, counts
