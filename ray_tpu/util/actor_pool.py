"""ActorPool (reference: ``python/ray/util/actor_pool.py``): round-robin a
set of actors over a stream of work items with ordered or unordered results."""

from __future__ import annotations

from typing import Any, Callable, Iterable, TypeVar

import ray_tpu

V = TypeVar("V")


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def map(self, fn: Callable, values: Iterable[V]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[V]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value: V):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout=None):
        if not self.has_next():
            raise StopIteration("No more results")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = ray_tpu.get(future, timeout=timeout)
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return value

    def get_next_unordered(self, timeout=None):
        if not self.has_next():
            raise StopIteration("No more results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        i, actor = self._future_to_actor.pop(future)
        del self._index_to_future[i]
        self._next_return_index = max(self._next_return_index, i + 1)
        value = ray_tpu.get(future)
        self._return_actor(actor)
        return value

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def pop_idle(self):
        return self._idle.pop() if self.has_free() else None

    def push(self, actor):
        self._return_actor(actor)
