"""Task/actor tracing (reference: ``python/ray/util/tracing/tracing_helper.py``
— OpenTelemetry spans around submit/execute when RAY_TRACING_ENABLED).

OpenTelemetry isn't bundled, so spans are recorded into the head's task-event
stream instead: every task already carries PENDING/RUNNING/FINISHED
transitions with timestamps (``head.task_events``), which ``timeline()``
exports as a Chrome trace. This module adds the *user-defined* span surface
on top: application code brackets its own regions and they land in the same
timeline, nested per process/actor.

    from ray_tpu.util import tracing

    with tracing.span("preprocess", batch=i):
        ...

``tracing.export_chrome_trace(path)`` merges runtime task events, user
spans, and flight-recorder request events into one chrome://tracing-loadable
JSON file — with one lane per request for everything that carries a
``request_id``.

**Trace context.** A request_id is minted at the serve proxy (or by
``trace_context()`` in application code, or implicitly at ``remote()``
submission) and carried as a per-thread context: ``remote()`` /
actor-method submissions stamp it into the task spec, the executing worker
re-installs it around the task body, and every ``span``/flight-recorder
event recorded underneath is tagged with it.  One request's life across
proxy → router → replica → engine is thereby a single correlated trace
(``python -m ray_tpu.obs req <id>``).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Iterator, Optional


def _env_max_spans() -> int:
    """Span retention cap (``RAY_TPU_TRACE_MAX_SPANS``): always-on tracing
    in a long-lived engine process must be bounded — before this cap the
    span list grew without limit for the process's lifetime."""
    try:
        return max(16, int(os.environ.get("RAY_TPU_TRACE_MAX_SPANS", "8192")))
    except ValueError:
        return 8192


def _env_sample_rate() -> float:
    """Head-sampling rate (``RAY_TPU_TRACE_SAMPLE``, 0..1, default 1.0):
    the keep/drop decision is made once per request id, deterministically
    from the id itself, so every process in the request's path agrees
    without coordination (no half-sampled traces)."""
    try:
        return min(1.0, max(0.0, float(os.environ.get("RAY_TPU_TRACE_SAMPLE", "1"))))
    except ValueError:
        return 1.0


_local = threading.local()
_lock = threading.Lock()
# finished spans of THIS process: bounded drop-oldest ring
_spans: deque = deque(maxlen=_env_max_spans())
_dropped_spans = 0
_drop_counter = None  # lazy metrics.Counter — created on first drop only


def _now_us() -> float:
    return time.time() * 1e6


def configure(max_spans: Optional[int] = None) -> None:
    """Resize the span ring (tests/tuning; keeps the newest spans)."""
    global _spans
    if max_spans is not None:
        with _lock:
            _spans = deque(_spans, maxlen=max(16, int(max_spans)))


def span_stats() -> dict:
    with _lock:
        return {
            "capacity": _spans.maxlen,
            "size": len(_spans),
            "dropped": _dropped_spans,
        }


def _count_dropped_span() -> None:
    # caller holds _lock; the metric is created lazily so processes that
    # never hit the cap never pay for a metrics registry entry
    global _dropped_spans, _drop_counter
    _dropped_spans += 1
    if _drop_counter is None:
        try:
            from ray_tpu.util.metrics import Counter

            _drop_counter = Counter(
                "tracing_dropped_spans",
                "spans evicted by the per-process retention cap",
            )
        except Exception:
            _drop_counter = False  # metrics unavailable: stats() still counts
    if _drop_counter:
        try:
            _drop_counter.inc()
        except Exception:
            pass


def trace_sampled(request_id: Optional[str]) -> bool:
    """Head-sampling decision for a request id (None = unsampled-context
    spans, always kept). Deterministic across processes: the id's leading
    hex bits against the sample rate."""
    rate = _env_sample_rate()
    if rate >= 1.0 or not request_id:
        return True
    if rate <= 0.0:
        return False
    try:
        bits = int(request_id[:8], 16)
    except ValueError:
        bits = hash(request_id) & 0xFFFFFFFF
    return bits / 0xFFFFFFFF < rate


# ---------------------------------------------------------------------------
# trace context (request_id propagation)
# ---------------------------------------------------------------------------


def new_request_id() -> str:
    """Mint a fresh request id (16 hex chars — short enough to grep, wide
    enough to never collide within a cluster's lifetime)."""
    return uuid.uuid4().hex[:16]


def get_trace_context() -> Optional[dict]:
    """The calling thread's active trace context ({"request_id": ...}) or
    None. Shipped in task specs by remote()/actor submissions."""
    return getattr(_local, "trace_ctx", None)


def set_trace_context(ctx: Optional[dict]) -> Optional[dict]:
    """Install (or clear, with None) the thread's trace context; returns
    the previous one so callers can restore it."""
    prev = getattr(_local, "trace_ctx", None)
    _local.trace_ctx = ctx
    return prev


def current_request_id() -> Optional[str]:
    ctx = getattr(_local, "trace_ctx", None)
    return ctx.get("request_id") if ctx else None


@contextlib.contextmanager
def trace_context(request_id: Optional[str] = None) -> Iterator[str]:
    """Scope a request id onto this thread (minting one if not given);
    spans, flight-recorder events, and remote() hops underneath carry it."""
    rid = request_id or new_request_id()
    prev = set_trace_context({"request_id": rid})
    try:
        yield rid
    finally:
        set_trace_context(prev)


@contextlib.contextmanager
def span(name: str, **attributes: Any) -> Iterator[None]:
    """Record a named region. Nesting tracks a per-thread stack so child
    spans indent under their parent in the trace viewer. An active trace
    context tags the span with its request_id (one lane per request in
    the exported trace)."""
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    t0 = _now_us()
    try:
        yield
    finally:
        _local.depth = depth
        rec = {
            "name": name,
            "cat": "user",
            "ph": "X",
            "ts": t0,
            "dur": _now_us() - t0,
            "pid": f"proc-{os.getpid()}",
            "tid": f"thread-{threading.get_ident() & 0xFFFF}-d{depth}",
        }
        rid = current_request_id()
        if attributes or rid:
            args = {k: _jsonable(v) for k, v in attributes.items()}
            if rid:
                args.setdefault("request_id", rid)
            rec["args"] = args
        if trace_sampled(rid):
            with _lock:
                if len(_spans) == _spans.maxlen:
                    _count_dropped_span()
                _spans.append(rec)


def _jsonable(v: Any):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return repr(v)


def get_spans() -> list[dict]:
    """Finished user spans recorded in this process."""
    with _lock:
        return list(_spans)


def clear() -> None:
    with _lock:
        _spans.clear()


def collect_cluster_spans() -> list[dict]:
    """Gather user spans from every live worker (a task per node would be
    overkill; workers ship spans through a collector task)."""
    import ray_tpu

    @ray_tpu.remote
    def _drain():
        from ray_tpu.util import tracing as t

        out = t.get_spans()
        t.clear()
        return out

    # best effort: one collector task (workers sharing that process drain);
    # driver-local spans are always included
    out = list(get_spans())
    try:
        out += ray_tpu.get(_drain.remote(), timeout=10)
    except Exception:
        pass
    return out


def request_lanes(
    spans: list[dict], recorder_events: list[dict]
) -> list[dict]:
    """Chrome-trace entries giving each request its own lane: spans whose
    args carry a request_id are mirrored into pid="requests"/tid=<id>, and
    flight-recorder events with a request_id become instant markers on the
    same lane — proxy→replica→engine spans plus per-token events line up
    under one request.

    Single-entry ids are NOT mirrored: every rootless ``remote()``
    submission auto-mints a request_id, so a plain 50k-task batch job
    would otherwise double its trace into 50k one-slice lanes.  A lane
    only earns its row when the id correlates at least two records —
    which every served/multi-hop request does."""
    counts: dict[str, int] = {}
    for s in spans:
        rid = (s.get("args") or {}).get("request_id")
        if rid:
            counts[rid] = counts.get(rid, 0) + 1
    for ev in recorder_events:
        rid = ev.get("request_id")
        if rid:
            counts[rid] = counts.get(rid, 0) + 1
    lanes: list[dict] = []
    for s in spans:
        rid = (s.get("args") or {}).get("request_id")
        if not rid or counts[rid] < 2:
            continue
        lanes.append({**s, "pid": "requests", "tid": f"req:{rid}"})
    for ev in recorder_events:
        rid = ev.get("request_id")
        if not rid or counts[rid] < 2:
            continue
        args = {
            k: v
            for k, v in ev.items()
            if k not in ("ts", "type", "seq", "request_id")
        }
        lanes.append(
            {
                "name": ev.get("type", "event"),
                "cat": "request",
                "ph": "i",
                "s": "t",  # thread-scoped instant marker
                "ts": ev.get("ts", 0.0) * 1e6,
                "pid": "requests",
                "tid": f"req:{rid}",
                "args": args,
            }
        )
    return lanes


def export_chrome_trace(path: Optional[str] = None) -> list[dict]:
    """Runtime task events + user spans + per-request lanes as one Chrome
    trace (reference: ``ray timeline``, ``_private/state.py:924``). Every
    span/flight-recorder event carrying a request_id additionally lands in
    a ``requests``-group lane keyed by its id, so one request's whole life
    reads as a single row in chrome://tracing / Perfetto."""
    from ray_tpu._private import events as ev
    from ray_tpu.util import state as st

    spans = st.timeline() + collect_cluster_spans()
    recorder = ev.collect_cluster_events()
    events = spans + request_lanes(spans, recorder)
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events
