"""Task/actor tracing (reference: ``python/ray/util/tracing/tracing_helper.py``
— OpenTelemetry spans around submit/execute when RAY_TRACING_ENABLED).

OpenTelemetry isn't bundled, so spans are recorded into the head's task-event
stream instead: every task already carries PENDING/RUNNING/FINISHED
transitions with timestamps (``head.task_events``), which ``timeline()``
exports as a Chrome trace. This module adds the *user-defined* span surface
on top: application code brackets its own regions and they land in the same
timeline, nested per process/actor.

    from ray_tpu.util import tracing

    with tracing.span("preprocess", batch=i):
        ...

``tracing.export_chrome_trace(path)`` merges runtime task events and user
spans into one chrome://tracing-loadable JSON file.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Iterator, Optional

_local = threading.local()
_lock = threading.Lock()
_spans: list[dict] = []  # finished spans of THIS process


def _now_us() -> float:
    return time.time() * 1e6


@contextlib.contextmanager
def span(name: str, **attributes: Any) -> Iterator[None]:
    """Record a named region. Nesting tracks a per-thread stack so child
    spans indent under their parent in the trace viewer."""
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    t0 = _now_us()
    try:
        yield
    finally:
        _local.depth = depth
        rec = {
            "name": name,
            "cat": "user",
            "ph": "X",
            "ts": t0,
            "dur": _now_us() - t0,
            "pid": f"proc-{os.getpid()}",
            "tid": f"thread-{threading.get_ident() & 0xFFFF}-d{depth}",
        }
        if attributes:
            rec["args"] = {k: _jsonable(v) for k, v in attributes.items()}
        with _lock:
            _spans.append(rec)


def _jsonable(v: Any):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return repr(v)


def get_spans() -> list[dict]:
    """Finished user spans recorded in this process."""
    with _lock:
        return list(_spans)


def clear() -> None:
    with _lock:
        _spans.clear()


def collect_cluster_spans() -> list[dict]:
    """Gather user spans from every live worker (a task per node would be
    overkill; workers ship spans through a collector task)."""
    import ray_tpu

    @ray_tpu.remote
    def _drain():
        from ray_tpu.util import tracing as t

        out = t.get_spans()
        t.clear()
        return out

    # best effort: one collector task (workers sharing that process drain);
    # driver-local spans are always included
    out = list(get_spans())
    try:
        out += ray_tpu.get(_drain.remote(), timeout=10)
    except Exception:
        pass
    return out


def export_chrome_trace(path: Optional[str] = None) -> list[dict]:
    """Runtime task events + user spans as one Chrome trace
    (reference: ``ray timeline``, ``_private/state.py:924``)."""
    from ray_tpu.util import state as st

    events = st.timeline() + collect_cluster_spans()
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events
