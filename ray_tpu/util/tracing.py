"""Task/actor tracing (reference: ``python/ray/util/tracing/tracing_helper.py``
— OpenTelemetry spans around submit/execute when RAY_TRACING_ENABLED).

OpenTelemetry isn't bundled, so spans are recorded into the head's task-event
stream instead: every task already carries PENDING/RUNNING/FINISHED
transitions with timestamps (``head.task_events``), which ``timeline()``
exports as a Chrome trace. This module adds the *user-defined* span surface
on top: application code brackets its own regions and they land in the same
timeline, nested per process/actor.

    from ray_tpu.util import tracing

    with tracing.span("preprocess", batch=i):
        ...

``tracing.export_chrome_trace(path)`` merges runtime task events, user
spans, and flight-recorder request events into one chrome://tracing-loadable
JSON file — with one lane per request for everything that carries a
``request_id``.

**Trace context.** A request_id is minted at the serve proxy (or by
``trace_context()`` in application code, or implicitly at ``remote()``
submission) and carried as a per-thread context: ``remote()`` /
actor-method submissions stamp it into the task spec, the executing worker
re-installs it around the task body, and every ``span``/flight-recorder
event recorded underneath is tagged with it.  One request's life across
proxy → router → replica → engine is thereby a single correlated trace
(``python -m ray_tpu.obs req <id>``).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterator, Optional


def _env_max_spans() -> int:
    """Span retention cap (``RAY_TPU_TRACE_MAX_SPANS``): always-on tracing
    in a long-lived engine process must be bounded — before this cap the
    span list grew without limit for the process's lifetime."""
    try:
        return max(16, int(os.environ.get("RAY_TPU_TRACE_MAX_SPANS", "8192")))
    except ValueError:
        return 8192


_rate_cache: tuple = ("1", 1.0)  # (raw env string, parsed) — parse once


def _env_sample_rate() -> float:
    """Head-sampling rate (``RAY_TPU_TRACE_SAMPLE``, 0..1, default 1.0):
    the keep/drop decision is made once per request id, deterministically
    from the id itself, so every process in the request's path agrees
    without coordination (no half-sampled traces). The float parse is
    cached keyed on the raw env string — this sits on the span/context
    hot path, and tests retune the env on a live process."""
    global _rate_cache
    raw = os.environ.get("RAY_TPU_TRACE_SAMPLE", "1")
    cached_raw, cached = _rate_cache
    if raw == cached_raw:
        return cached
    try:
        rate = min(1.0, max(0.0, float(raw)))
    except ValueError:
        rate = 1.0
    _rate_cache = (raw, rate)
    return rate


_local = threading.local()
_lock = threading.Lock()
# finished spans of THIS process: bounded drop-oldest ring
_spans: deque = deque(maxlen=_env_max_spans())
_dropped_spans = 0
_drop_counter = None  # lazy metrics.Counter — created on first drop only


def _now_us() -> float:
    return time.time() * 1e6


def configure(max_spans: Optional[int] = None) -> None:
    """Resize the span ring (tests/tuning; keeps the newest spans)."""
    global _spans
    if max_spans is not None:
        with _lock:
            _spans = deque(_spans, maxlen=max(16, int(max_spans)))


def span_stats() -> dict:
    with _lock:
        return {
            "capacity": _spans.maxlen,
            "size": len(_spans),
            "dropped": _dropped_spans,
        }


def _count_dropped_span() -> None:
    # caller holds _lock; the metric is created lazily so processes that
    # never hit the cap never pay for a metrics registry entry
    global _dropped_spans, _drop_counter
    _dropped_spans += 1
    if _drop_counter is None:
        from ray_tpu.util.metrics import safe_counter

        # False (not None) when unavailable: don't retry every drop
        _drop_counter = safe_counter(
            "tracing_dropped_spans",
            "spans evicted by the per-process retention cap",
        ) or False
    if _drop_counter:
        try:
            _drop_counter.inc()
        except Exception:
            pass


def trace_sampled(request_id: Optional[str]) -> bool:
    """Head-sampling decision for a request id (None = unsampled-context
    spans, always kept). Deterministic across processes: the id's leading
    hex bits against the sample rate."""
    rate = _env_sample_rate()
    if rate >= 1.0 or not request_id:
        return True
    if rate <= 0.0:
        return False
    try:
        bits = int(request_id[:8], 16)
    except ValueError:
        bits = hash(request_id) & 0xFFFFFFFF
    return bits / 0xFFFFFFFF < rate


# ---------------------------------------------------------------------------
# trace context (request_id propagation)
#
# Three context shapes ride the per-thread slot (PR-11 zero-cost rebuild):
#
# * a plain dict ``{"request_id": rid}`` — a SAMPLED context: propagated in
#   task specs, tags spans/events (the pre-PR-11 shape, still the
#   compatibility contract for hand-installed contexts);
# * :class:`UnsampledContext` — the head-sampling decision said "drop",
#   made ONCE at mint. It is an immutable token: spans under it skip
#   allocation/locking entirely, ``remote()`` skips spec tagging (no
#   cross-process shipping), and nothing downstream pays for tracing.
# * :class:`LazyTaskContext` — a rootless task executing on a worker. The
#   task-id-rooted request id (and its sampling decision) materialize only
#   when something actually asks (an event, a span, a nested submission) —
#   a plain noop task pays ZERO context cost end to end.
# ---------------------------------------------------------------------------


class UnsampledContext:
    """Immutable unsampled-trace token. Carries the request id so
    forensics stay correlated at ANY sample rate — ``record()`` events,
    head task-event rows, and `obs req <id>` all keep the request id;
    only SPANS are dropped, and they are dropped for free (the token
    short-circuits ``span()`` before any allocation). The token itself
    rides task specs — one shared immutable object per request, shipped
    by reference (no per-task dict copies) — so every downstream hop
    inherits the mint-time decision and half-sampled traces cannot
    happen (the module's no-coordination invariant)."""

    __slots__ = ("request_id",)
    sampled = False

    def __init__(self, request_id: Optional[str]):
        object.__setattr__(self, "request_id", request_id)

    def __setattr__(self, name, value):  # immutability: tokens are shared
        raise AttributeError("UnsampledContext is immutable")

    def __reduce__(self):  # __slots__ + frozen setattr need explicit pickle
        return (UnsampledContext, (self.request_id,))

    def get(self, key, default=None):  # dict-compatible read surface
        return self.request_id if key == "request_id" else default

    def __repr__(self):
        return f"UnsampledContext({self.request_id!r})"


class LazyTaskContext:
    """Rootless-task context: the request id derives from the task id the
    moment someone asks for it (and the sampling decision with it). Built
    worker-side for specs that carry no ``trace_ctx``."""

    __slots__ = ("_task_id", "_rid", "_sampled")

    def __init__(self, task_id: bytes):
        self._task_id = task_id
        self._rid = None
        self._sampled = None

    @property
    def request_id(self) -> str:
        rid = self._rid
        if rid is None:
            rid = self._rid = self._task_id.hex()[:16]
        return rid

    @property
    def sampled(self) -> bool:
        s = self._sampled
        if s is None:
            s = self._sampled = trace_sampled(self.request_id)
        return s

    def get(self, key, default=None):
        return self.request_id if key == "request_id" else default

    def __repr__(self):
        return f"LazyTaskContext({self.request_id!r})"


def new_request_id() -> str:
    """Mint a fresh request id (16 hex chars — short enough to grep, wide
    enough to never collide within a cluster's lifetime). ``os.urandom``
    rather than uuid4: same 64 bits of entropy at a fifth of the cost
    (this runs once per request on the serve hot path)."""
    return os.urandom(8).hex()


def mint_context(request_id: Optional[str] = None):
    """Build a context for ``request_id`` (minting an id if None), making
    the head-sampling decision HERE, once: sampled requests get the dict
    shape, unsampled requests get the cheap immutable token that every
    downstream hot path short-circuits on."""
    rid = request_id or new_request_id()
    if trace_sampled(rid):
        return {"request_id": rid}
    return UnsampledContext(rid)


def get_trace_context():
    """The calling thread's active trace context ({"request_id": ...}, an
    :class:`UnsampledContext`, a :class:`LazyTaskContext`) or None."""
    return getattr(_local, "trace_ctx", None)


def set_trace_context(ctx):
    """Install (or clear, with None) the thread's trace context; returns
    the previous one so callers can restore it."""
    prev = getattr(_local, "trace_ctx", None)
    _local.trace_ctx = ctx
    return prev


def context_sampled(ctx) -> bool:
    """Whether spans under ``ctx`` are kept. None (no context) keeps —
    context-less spans are always retained, as before."""
    if ctx is None:
        return True
    if type(ctx) is dict:
        # hand-installed dicts predate mint-time decisions: fall back to
        # the deterministic per-id check so sampling still applies
        return trace_sampled(ctx.get("request_id"))
    return ctx.sampled


def context_for_spec(ctx):
    """What ``remote()``/actor submission ships in ``spec["trace_ctx"]``
    for an active context: the dict or unsampled token itself (shipped
    by reference — no copy; the token keeps forensics correlated and
    pins the mint-time sampling decision downstream), or a context
    materialized from a lazy root — as a dict when its task-rooted id
    sampled, as a token when it didn't, so nested hops under a rootless
    root also inherit ONE coherent decision."""
    if type(ctx) is dict or type(ctx) is UnsampledContext:
        return ctx
    if type(ctx) is LazyTaskContext:
        if ctx.sampled:
            return {"request_id": ctx.request_id}
        return UnsampledContext(ctx.request_id)
    return None


def task_context(spec_ctx, task_id: bytes):
    """The context a worker installs around a task body: the submitter's
    shipped context when the spec carries one, else a lazy task-rooted
    context that costs nothing until observed."""
    if spec_ctx is not None:
        return spec_ctx
    return LazyTaskContext(task_id)


def current_request_id() -> Optional[str]:
    ctx = getattr(_local, "trace_ctx", None)
    if ctx is None:
        return None
    if type(ctx) is dict:
        return ctx.get("request_id")
    return ctx.request_id


@contextlib.contextmanager
def trace_context(request_id: Optional[str] = None) -> Iterator[str]:
    """Scope a request id onto this thread (minting one if not given);
    spans, flight-recorder events, and remote() hops underneath carry it.
    The sampling decision happens here, once per request."""
    ctx = mint_context(request_id)
    rid = ctx.get("request_id")  # both context shapes expose .get
    prev = set_trace_context(ctx)
    try:
        yield rid
    finally:
        set_trace_context(prev)


class _NullSpan:
    """Shared do-nothing span: what an unsampled request's ``span()``
    returns — no allocation, no clock read, no lock."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """A recording span (context manager). Nesting tracks a per-thread
    stack so child spans indent under their parent in the trace viewer;
    an active trace context tags the span's args with its request_id
    (one lane per request in the exported trace)."""

    __slots__ = ("name", "attributes", "t0", "depth")

    def __init__(self, name: str, attributes: dict):
        self.name = name
        self.attributes = attributes

    def __enter__(self):
        self.depth = depth = getattr(_local, "depth", 0)
        _local.depth = depth + 1
        self.t0 = _now_us()
        return None

    def __exit__(self, *exc):
        depth = self.depth
        _local.depth = depth
        rec = {
            "name": self.name,
            "cat": "user",
            "ph": "X",
            "ts": self.t0,
            "dur": _now_us() - self.t0,
            "pid": f"proc-{os.getpid()}",
            "tid": f"thread-{threading.get_ident() & 0xFFFF}-d{depth}",
        }
        rid = current_request_id()
        attributes = self.attributes
        if attributes or rid:
            args = {k: _jsonable(v) for k, v in attributes.items()}
            if rid:
                args.setdefault("request_id", rid)
            rec["args"] = args
        with _lock:
            if len(_spans) == _spans.maxlen:
                _count_dropped_span()
            _spans.append(rec)
        return False


def span(name: str, **attributes: Any):
    """Record a named region (``with tracing.span("step", batch=i):``).

    ZERO-COST when unsampled: the mint-time head-sampling decision lives
    on the context, so an unsampled request's spans return a shared null
    manager — no record dict, no clock reads, no span-ring lock; the
    body just runs."""
    ctx = getattr(_local, "trace_ctx", None)
    if ctx is not None and not context_sampled(ctx):
        return _NULL_SPAN
    return _Span(name, attributes)


def _jsonable(v: Any):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return repr(v)


def get_spans() -> list[dict]:
    """Finished user spans recorded in this process."""
    with _lock:
        return list(_spans)


def clear() -> None:
    with _lock:
        _spans.clear()


def collect_cluster_spans() -> list[dict]:
    """Gather user spans from every live worker (a task per node would be
    overkill; workers ship spans through a collector task)."""
    import ray_tpu

    @ray_tpu.remote
    def _drain():
        from ray_tpu.util import tracing as t

        out = t.get_spans()
        t.clear()
        return out

    # best effort: one collector task (workers sharing that process drain);
    # driver-local spans are always included
    out = list(get_spans())
    try:
        out += ray_tpu.get(_drain.remote(), timeout=10)
    except Exception:
        pass
    return out


def request_lanes(
    spans: list[dict], recorder_events: list[dict]
) -> list[dict]:
    """Chrome-trace entries giving each request its own lane: spans whose
    args carry a request_id are mirrored into pid="requests"/tid=<id>, and
    flight-recorder events with a request_id become instant markers on the
    same lane — proxy→replica→engine spans plus per-token events line up
    under one request.

    Single-entry ids are NOT mirrored: every rootless ``remote()``
    submission auto-mints a request_id, so a plain 50k-task batch job
    would otherwise double its trace into 50k one-slice lanes.  A lane
    only earns its row when the id correlates at least two records —
    which every served/multi-hop request does."""
    counts: dict[str, int] = {}
    for s in spans:
        rid = (s.get("args") or {}).get("request_id")
        if rid:
            counts[rid] = counts.get(rid, 0) + 1
    for ev in recorder_events:
        rid = ev.get("request_id")
        if rid:
            counts[rid] = counts.get(rid, 0) + 1
    lanes: list[dict] = []
    for s in spans:
        rid = (s.get("args") or {}).get("request_id")
        if not rid or counts[rid] < 2:
            continue
        lanes.append({**s, "pid": "requests", "tid": f"req:{rid}"})
    for ev in recorder_events:
        rid = ev.get("request_id")
        if not rid or counts[rid] < 2:
            continue
        args = {
            k: v
            for k, v in ev.items()
            if k not in ("ts", "type", "seq", "request_id")
        }
        lanes.append(
            {
                "name": ev.get("type", "event"),
                "cat": "request",
                "ph": "i",
                "s": "t",  # thread-scoped instant marker
                "ts": ev.get("ts", 0.0) * 1e6,
                "pid": "requests",
                "tid": f"req:{rid}",
                "args": args,
            }
        )
    return lanes


def export_chrome_trace(path: Optional[str] = None) -> list[dict]:
    """Runtime task events + user spans + per-request lanes as one Chrome
    trace (reference: ``ray timeline``, ``_private/state.py:924``). Every
    span/flight-recorder event carrying a request_id additionally lands in
    a ``requests``-group lane keyed by its id, so one request's whole life
    reads as a single row in chrome://tracing / Perfetto. Sampled tasks'
    folded waterfall records (util.waterfall) render as NESTED slices —
    a total-duration slice with the seven hop legs inside it — on a
    ``waterfall`` process group."""
    from ray_tpu._private import events as ev
    from ray_tpu.util import state as st

    spans = st.timeline() + collect_cluster_spans()
    recorder = ev.collect_cluster_events()
    events = spans + request_lanes(spans, recorder)
    try:
        from ray_tpu._private.runtime import get_ctx
        from ray_tpu.util import waterfall as _wf

        recent = get_ctx().call("waterfall", recent=_wf._RECENT_CAP)
        events += _wf.chrome_slices(recent.get("recent", []))
    except Exception:
        pass  # head without the waterfall rpc / no folded records
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events
