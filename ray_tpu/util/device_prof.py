"""Device-step profiler: per-jit-site wall time + runtime retrace detection.

Every jitted entry point in the serving/training hot loop (decode /
prefill / verify / fork in ``llm.model_runner``, the train step in
``train.trainer``) is supposed to trace ONCE per static shape and then
run from cache forever — that is the static-shape discipline the whole
engine is built on, and raylint RL014 (retrace-storm) enforces it
statically.  This module is RL014's **runtime twin**: it measures the
wall time of each call into a per-site histogram and watches the jit
cache size (``PjitFunction._cache_size``) so a site that RECOMPILES
after its warmup baseline emits a ``<family>.retrace`` flight-recorder
event and bumps the ``device_retraces`` counter — which the
``retrace-storm`` SLO rule (``util.slo``) turns into a firing alert.

Usage (one profiler per owner, so two engines in one process never
compare cache sizes of different function objects)::

    prof = JitProfiler(event="llm.retrace")
    t0 = time.perf_counter()
    out = self._decode(...)
    prof.note("decode", self._decode, time.perf_counter() - t0)

``note`` is an EMIT PATH under the PR-11 zero-cost contract: a dict
probe, one lock-free histogram observe, and a C-level cache-size read —
no shared locks (``tests/test_obs_hotpath.py`` extends the index-backed
lint fixture over it).  The retrace branch (event + counter) only runs
when a site actually recompiled, which steady-state engines never do.

The first ``note`` per site sets the baseline — by construction that is
the warmup call (``LLMEngine.warmup`` / the first train step), so
legitimate cold compiles never count as retraces.  A site whose shapes
genuinely vary (none should) fires exactly once per NEW trace: the
baseline advances to the observed cache size each time.
"""

from __future__ import annotations

import threading
from typing import Optional

#: raylint RL012 registry.  The retrace EVENT types are per-owner
#: (``JitProfiler(event="llm.retrace" | "train.retrace")``) — a dynamic
#: ``record(self.event, ...)`` site RL012 deliberately skips — and are
#: documented in OBSERVABILITY.md's event-family tables instead.
METRIC_NAMES = (
    "device_step_seconds",
    "device_retraces",
)

_METRICS = None
_METRICS_LOCK = threading.Lock()

#: boundaries spanning sub-ms cached dispatch through multi-second compiles
_STEP_BOUNDARIES = (
    1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _metrics() -> dict:
    global _METRICS
    if _METRICS is not None:
        return _METRICS
    with _METRICS_LOCK:
        if _METRICS is not None:
            return _METRICS
        from ray_tpu.util.metrics import Counter, Histogram

        _METRICS = {
            "seconds": Histogram(
                "device_step_seconds",
                "wall time per jitted entry-point call (decode/prefill/"
                "verify/fork/train_step), including any compile",
                boundaries=_STEP_BOUNDARIES,
                tag_keys=("site",),
            ),
            "retraces": Counter(
                "device_retraces",
                "jit sites that recompiled AFTER their warmup baseline — "
                "RL014's runtime twin; any nonzero rate trips the "
                "retrace-storm SLO rule",
                tag_keys=("site",),
            ),
        }
    return _METRICS


def _cache_size(fn) -> Optional[int]:
    """Compiled-executable count of a jitted callable, or None when the
    object doesn't expose one (plain callables in tests, future jax)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


class JitProfiler:
    """Per-owner step profiler.  ``note`` is the hot path; everything
    else (``stats``) is query-side."""

    __slots__ = ("event", "_sites", "_m")

    def __init__(self, event: str = "llm.retrace"):
        #: flight-recorder event type emitted on a retrace (``llm.retrace``
        #: for the serving engine, ``train.retrace`` for the train step)
        self.event = event
        # site -> [baseline cache size (None until known), calls, retraces];
        # single-writer in practice (the engine step / train loop thread),
        # and a racy double-count would only over-report — never a lock
        self._sites: dict[str, list] = {}
        self._m = _metrics()

    def note(self, site: str, fn, dur_s: float) -> bool:
        """Record one call of jit site ``site``; returns True when the
        call RETRACED an already-baselined site."""
        self._m["seconds"].observe(dur_s, tags={"site": site})
        st = self._sites.get(site)
        size = _cache_size(fn)
        if st is None:
            # first call per site == the warmup/compile call: baseline
            # here.  The zero-inc materializes the site's tagged series
            # BEFORE any retrace can happen — a window delta needs a
            # pre-storm sample to diff against, so without it the first
            # storm of a site would never trip the retrace-storm SLO
            self._sites[site] = [size, 1, 0]
            self._m["retraces"].inc(0.0, tags={"site": site})
            return False
        st[1] += 1
        if size is None or st[0] is None or size <= st[0]:
            if st[0] is None:
                st[0] = size
            return False
        # recompile after warmup: advance the baseline so each NEW trace
        # fires exactly once, then take the (cold) reporting path
        st[0] = size
        st[2] += 1
        self._m["retraces"].inc(tags={"site": site})
        from ray_tpu._private import events as _events

        _events.record(
            self.event, site=site, cache_size=size,
            call_n=st[1], dur_s=round(dur_s, 6),
        )
        return True

    def stats(self) -> dict:
        """Per-site ``{"calls", "retraces", "cache_size"}`` (query side)."""
        return {
            site: {"cache_size": st[0], "calls": st[1], "retraces": st[2]}
            for site, st in self._sites.items()
        }

    @property
    def retraces(self) -> int:
        return sum(st[2] for st in self._sites.values())
