"""Scheduling strategies (reference: ``python/ray/util/scheduling_strategies.py:15-135``).

``"DEFAULT"`` — hybrid pack/spread; ``"SPREAD"`` — least-utilized node;
``PlacementGroupSchedulingStrategy`` — run inside a reserved bundle;
``NodeAffinitySchedulingStrategy`` — pin to a node (hard or soft).
On TPU pods, placement groups are the slice-aware primitive: a STRICT_PACK
group over a slice's hosts keeps a mesh's participants inside one ICI domain.
"""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group,
        placement_group_bundle_index: Optional[int] = None,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelSchedulingStrategy:
    """Label-based node selection (reference node-label policy); hard
    requirements only in this round."""

    def __init__(self, hard: Optional[dict] = None, soft: Optional[dict] = None):
        self.hard = hard or {}
        self.soft = soft or {}
