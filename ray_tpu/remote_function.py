"""Remote functions: ``@ray_tpu.remote`` on a plain function.

Counterpart of the reference's ``python/ray/remote_function.py`` —
``RemoteFunction._remote`` (:262) pickles the function once into the cluster
function table, resolves options, and submits a task spec; ``.options(...)``
returns a shallow override copy.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

from ray_tpu._private import options as opt
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.runtime import get_ctx
from ray_tpu.util import tracing as _tracing
from ray_tpu.util import waterfall as _waterfall


class RemoteFunction:
    def __init__(self, fn, default_options: Optional[dict] = None):
        if not callable(fn):
            raise TypeError("@remote must decorate a callable")
        self._fn = fn
        self._options = default_options or {}
        opt.validate(self._options, is_actor=False)
        self._blob: Optional[bytes] = None
        self._func_id: Optional[bytes] = None  # sha1(blob), hashed once
        self._spec_template: Optional[dict] = None
        functools.update_wrapper(self, fn)

    def _template(self) -> dict:
        """Static per-(fn, options) spec fields, computed once — option
        resolution (resource folding, strategy validation) off the
        per-.remote() hot path. Values are shared by reference across
        submissions; the head treats spec contents as read-only (the only
        per-dispatch key, _pg_bundle, is set on the per-call spec copy)."""
        tpl = self._spec_template
        if tpl is None:
            o = self._options
            num_returns = o.get("num_returns", 1)
            tpl = self._spec_template = {
                "kind": "task",
                "num_returns": num_returns,
                "resources": opt.to_resources(o, is_actor=False),
                "strategy": opt.to_strategy(o),
                # streaming tasks never retry: items already handed to the
                # consumer cannot be un-consumed (reference disables lineage
                # reconstruction for streaming generators the same way).
                # None = not pinned by options: resolved against the LIVE
                # config at each submission (the config is mutable).
                "max_retries": 0
                if num_returns == "streaming"
                else o.get("max_retries"),
                "name": o.get("name") or getattr(self._fn, "__qualname__", "task"),
            }
            # head-side hot-path caches, template-constant so computed once
            # per (fn, options) instead of per submit: effective (non-zero)
            # resources and the scheduling signature. Must mirror
            # _PendingQueue._sig(spec) exactly — label_selector is folded
            # into strategy by to_strategy and never a spec key, so the
            # label slot is always None for template-built specs
            tpl["_eres"] = {k: v for k, v in tpl["resources"].items() if v != 0}
            tpl["_sig0"] = (
                tuple(sorted((k, v) for k, v in tpl["resources"].items() if v != 0)),
                tuple(tpl["strategy"]) if tpl["strategy"] else None,
                None,
                False,
            )
            # no-arg calls resolve to these SAME constants in
            # serialize_args, so the header identity-elision drops
            # args/kwargs from the steady-state wire body entirely
            from ray_tpu._private.runtime import EMPTY_ARGS, EMPTY_KWARGS

            tpl["args"] = EMPTY_ARGS
            tpl["kwargs"] = EMPTY_KWARGS
        return tpl

    def __call__(self, *a, **k):
        raise TypeError(
            f"Remote function {self._fn.__name__}() cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )

    def options(self, **new_options) -> "RemoteFunction":
        merged = {**self._options, **new_options}
        rf = RemoteFunction(self._fn, merged)
        rf._blob = self._blob
        return rf

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, options):
        ctx = get_ctx()
        if self._blob is None:
            self._blob = ser.dumps(self._fn)
        # the sha1 is per-(fn) constant: hash once here, let the context
        # intern the id (upload_function's per-ctx cache still decides
        # whether THIS cluster has seen the blob)
        func_id = ctx.upload_function(self._blob, self._func_id)
        self._func_id = func_id
        if options is self._options:
            tpl = self._template()
        else:  # explicit options dict (DAG execution paths)
            tpl = RemoteFunction(self._fn, options)._template()
        if "_hdr" not in tpl and tpl is self._spec_template:
            # spec header (cheaper per-task bytes, ISSUE 14): the static
            # per-(fn, options) fields ship once per connection/worker and
            # steady-state submissions reference them by id. func_id is
            # interned by upload_function, so identity-elision holds. Only
            # the CACHED template gets one — a throwaway options-override
            # template would mint a fresh header id per call and bloat
            # every receiver's header cache. The id is CONTENT-derived
            # (func_id + the stable option fields), so every process that
            # deserializes this function mints the SAME id and receiver
            # caches dedupe; racing first calls build identical headers.
            fields = dict(tpl)
            fields.pop("_hdr", None)  # racing first calls must not nest
            fields["func_id"] = func_id
            hid = ser.spec_header_id(
                b"task",
                func_id,
                sorted(
                    (k, v)
                    for k, v in fields.items()
                    if k in ("resources", "strategy", "num_returns",
                             "max_retries", "name", "kind")
                ),
            )
            tpl["_hdr"] = (hid, fields)
        num_returns = tpl["num_returns"]
        streaming = num_returns == "streaming"
        # trace-context propagation (util.tracing): a submission under an
        # active context ships it BY REFERENCE (sampled dict or shared
        # unsampled token — the token keeps request-id forensics intact
        # downstream while spans stay free); with no context at all the
        # executing worker roots a lazy trace at the task's own id, so
        # every task tree stays traceable without the submitter paying a
        # per-task id mint
        tctx = _tracing.get_trace_context()
        sp_ctx = _tracing.context_for_spec(tctx) if tctx is not None else None
        # task-hop waterfall (util.waterfall): SAMPLED request/reply tasks
        # carry phase stamps; everything else ships nothing and pays one
        # type check (streaming tasks reply long after exec — no waterfall)
        wf = None if streaming else _waterfall.maybe_start(sp_ctx)
        s_args, s_kwargs = ctx.serialize_args(args, kwargs)
        if wf is not None:
            _waterfall.stamp(wf)  # serialize: args done, spec build next
        task_id, return_ids = ctx.new_task_returns(
            1 if streaming else max(num_returns, 1)
        )
        spec = {
            **tpl,
            "task_id": task_id,
            "func_id": func_id,
            "args": s_args,
            "kwargs": s_kwargs,
            "return_ids": return_ids,
        }
        if sp_ctx is not None:
            spec["trace_ctx"] = sp_ctx
        if wf is not None:
            spec["wf"] = wf
        ns = getattr(ctx, "namespace", "default")
        if ns != "default":
            # tasks inherit the submitter's namespace (reference: job-scoped
            # namespaces): get_actor / named-actor creation inside the task
            # resolves in the client session's namespace, not "default"
            spec["namespace"] = ns
        if spec["max_retries"] is None:
            spec["max_retries"] = GLOBAL_CONFIG.default_max_retries
        if options.get("runtime_env"):
            from ray_tpu._private import runtime_env as renv

            spec["runtime_env"] = renv.package(options["runtime_env"], ctx)
        refs = ctx.submit_task(spec)
        if streaming:
            from ray_tpu._private.runtime import ObjectRefGenerator

            return ObjectRefGenerator(task_id, refs[0], ctx)
        if num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG-node construction (reference: dag/dag_node.py)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)


def remote_decorator(args: tuple, kwargs: dict[str, Any]):
    """Implements both ``@remote`` and ``@remote(**opts)`` for functions and
    classes (dispatch mirrors reference ``python/ray/_private/worker.py`` remote)."""
    from ray_tpu.actor import ActorClass

    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target, {})
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, dict(kwargs))
        return RemoteFunction(target, dict(kwargs))

    return wrap
