from ray_tpu.scripts import main

raise SystemExit(main())
