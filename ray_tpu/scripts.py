"""CLI: ``python -m ray_tpu <command>``.

Reference: ``python/ray/scripts/scripts.py:566`` (``ray start --head`` /
``ray start --address=`` node launcher) and the state CLI
(``util/state/state_cli.py`` — ``ray summary``, ``ray list``, ``ray
timeline``). The head command hosts the cluster head in THIS process
(listening on unix socket + TCP); the node command joins this machine to a
remote head via the node agent.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time


def _parse_resources(raw):
    res = json.loads(raw) if raw else {}
    if not isinstance(res, dict):
        raise SystemExit("--resources must be a JSON object")
    return res


def cmd_start(args) -> int:
    from ray_tpu._private.config import resolve_authkey

    authkey = resolve_authkey()
    if args.head:
        from ray_tpu._private.head import Head

        session = tempfile.mkdtemp(prefix="ray_tpu_head_")
        head = Head(os.path.join(session, "head.sock"), authkey=authkey)
        head.start()
        host, port = head.listen_tcp(args.host, args.port)
        res = _parse_resources(args.resources)
        res.setdefault("CPU", float(args.num_cpus or os.cpu_count() or 1))
        from ray_tpu.accelerators import tpu as tpu_accel

        chips = tpu_accel.detect_num_chips()
        if chips:
            res.setdefault("TPU", float(chips))
        head.add_node(res)
        print(f"ray_tpu head listening on {host}:{port}")
        print(f"  attach a node:   python -m ray_tpu start --address={host}:{port}")
        print(f"  attach a driver: ray_tpu.init(address=\"{host}:{port}\")")
        sys.stdout.flush()
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        signal.signal(signal.SIGINT, lambda *a: stop.append(1))
        try:
            while not stop:
                time.sleep(0.2)
        finally:
            head.shutdown()
        return 0

    if not args.address:
        raise SystemExit("pass --head to start a head, or --address=HOST:PORT to join one")
    from ray_tpu._private.node_agent import NodeAgent

    res = _parse_resources(args.resources)
    if args.num_cpus:
        res.setdefault("CPU", float(args.num_cpus))
    labels = json.loads(args.labels) if getattr(args, "labels", None) else None
    agent = NodeAgent(args.address, authkey, resources=res or None, labels=labels)
    print(f"ray_tpu node joined {args.address} as {agent.node_id_bin.hex()[:12]}")
    sys.stdout.flush()
    agent.run()
    return 0


def _attached(address):
    import ray_tpu

    ray_tpu.init(address=address)
    return ray_tpu


def cmd_summary(args) -> int:
    from ray_tpu.util import state

    ray_tpu = _attached(args.address)
    print(json.dumps(state.summary(), indent=2, default=str))
    ray_tpu.shutdown()
    return 0


def cmd_list(args) -> int:
    from ray_tpu.util import state

    ray_tpu = _attached(args.address)
    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "objects": state.list_objects,
        "nodes": state.list_nodes,
        "placement-groups": state.list_placement_groups,
    }[args.kind]
    print(json.dumps(fn(), indent=2, default=str))
    ray_tpu.shutdown()
    return 0


def cmd_stacks(args) -> int:
    """Dump every worker's thread stacks (debugging stuck workers —
    reference: `ray stack` / dashboard py-spy dumps)."""
    from ray_tpu.util import state

    ray_tpu = _attached(args.address)
    stacks = state.get_worker_stacks()
    for node, per_pid in stacks.items():
        for pid, text in per_pid.items():
            print(f"==== node {node} worker {pid} ====")
            print(text)
    ray_tpu.shutdown()
    return 0


def cmd_profile(args) -> int:
    """Sampling CPU profile of every worker; prints collapsed stacks
    (pipe a section into flamegraph.pl — reference: `ray timeline`-era
    dashboard py-spy cpu_profile)."""
    from ray_tpu.util import state

    ray_tpu = _attached(args.address)
    prof = state.profile_workers(
        duration_s=args.seconds, interval_ms=1000.0 / max(args.rate, 1.0)
    )
    for node, per_pid in prof.items():
        for err in per_pid.pop("_errors", []):
            print(f"==== node {node}: {err} ====", file=sys.stderr)
        for pid, text in per_pid.items():
            print(f"==== node {node} worker {pid} ====")
            print(text)
    ray_tpu.shutdown()
    return 0


def cmd_nodestats(args) -> int:
    from ray_tpu.util import state

    ray_tpu = _attached(args.address)
    print(json.dumps(state.get_node_stats(), indent=2, default=str))
    ray_tpu.shutdown()
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu.util import state

    ray_tpu = _attached(args.address)
    trace = state.timeline(args.output)
    print(f"wrote {len(trace)} events to {args.output}")
    ray_tpu.shutdown()
    return 0


def cmd_grafana(args) -> int:
    """Emit the generated Grafana dashboard JSON (util/grafana.py;
    reference: grafana_dashboard_factory.py). No cluster needed."""
    from ray_tpu.util.grafana import dashboard_json

    text = json.dumps(dashboard_json(), indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {args.output}")
    else:
        print(text)
    return 0


def cmd_status(args) -> int:
    ray_tpu = _attached(args.address)
    print(json.dumps(
        {
            "nodes": ray_tpu.nodes(),
            "cluster_resources": ray_tpu.cluster_resources(),
            "available_resources": ray_tpu.available_resources(),
        },
        indent=2,
        default=str,
    ))
    ray_tpu.shutdown()
    return 0


def cmd_job(args) -> int:
    from ray_tpu import job as joblib

    ray_tpu = _attached(args.address)
    try:
        if args.action == "submit":
            entry = list(args.entrypoint or [])
            if entry and entry[0] == "--":
                entry = entry[1:]  # strip only argparse's leading separator
            if not entry:
                raise SystemExit("job submit needs an entrypoint after --")
            import shlex

            jid = joblib.submit_job(" ".join(shlex.quote(a) for a in entry))
            print(jid)
        elif args.action == "list":
            print(json.dumps(joblib.list_jobs(), indent=2, default=str))
        else:
            if not args.job_id:
                raise SystemExit("--job-id required")
            if args.action == "status":
                print(joblib.get_job_status(args.job_id))
            elif args.action == "logs":
                sys.stdout.write(joblib.get_job_logs(args.job_id))
            elif args.action == "stop":
                print(joblib.stop_job(args.job_id))
    finally:
        ray_tpu.shutdown()
    return 0


def cmd_up(args) -> int:
    """Launch a cluster from YAML: head in this process + autoscaler loop
    (reference: `ray up` in autoscaler/_private/commands.py)."""
    from ray_tpu._private.config import resolve_authkey
    from ray_tpu._private.head import Head
    from ray_tpu.autoscaler.cluster_config import (
        build_provider,
        load_cluster_config,
        run_cluster,
    )

    cfg = load_cluster_config(args.config)
    head_cfg = cfg.get("head") or {}
    session = tempfile.mkdtemp(prefix="ray_tpu_head_")
    head = Head(os.path.join(session, "head.sock"), authkey=resolve_authkey())
    head.start()
    host, port = head.listen_tcp(
        head_cfg.get("host", "127.0.0.1"), int(head_cfg.get("port", 0))
    )
    head.add_node({"CPU": float(head_cfg.get("num_cpus", os.cpu_count() or 1))})
    print(f"[{cfg['cluster_name']}] head listening on {host}:{port}")
    print(
        "  worker join: python -m ray_tpu start "
        f"--address={host}:{port} "
        "--labels '{\"provider_node_id\": \"'$(hostname)'\"}'"
    )
    sys.stdout.flush()
    cluster = None
    if cfg["provider"]["type"] == "fake":
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(head=head)
    provider = build_provider(cfg, cluster=cluster)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        counts = run_cluster(
            cfg,
            head,
            provider,
            max_ticks=args.ticks,
            stop_check=lambda: bool(stop),
        )
        print(f"[{cfg['cluster_name']}] instances: {json.dumps(counts)}")
    finally:
        head.shutdown()
    return 0


def cmd_down(args) -> int:
    from ray_tpu.autoscaler.cluster_config import load_cluster_config, teardown_cluster

    cfg = load_cluster_config(args.config)
    gone = teardown_cluster(cfg)
    print(f"[{cfg['cluster_name']}] terminated {len(gone)} instance(s)")
    for name in gone:
        print(f"  {name}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start", help="start a head or join a cluster as a node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--address", help="HOST:PORT of a running head (node mode)")
    p.add_argument("--num-cpus", type=int)
    p.add_argument("--resources", help="JSON resource dict")
    p.add_argument("--labels", help="JSON node labels (e.g. provider_node_id)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("config", help="path to cluster YAML")
    p.add_argument(
        "--ticks",
        type=int,
        help="run N autoscaler reconcile ticks then exit (default: forever)",
    )
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="terminate every cluster VM from a YAML config")
    p.add_argument("config", help="path to cluster YAML")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("summary", help="cluster state summary")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("list", help="list tasks/actors/objects/nodes/placement-groups")
    p.add_argument("kind", choices=["tasks", "actors", "objects", "nodes", "placement-groups"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("timeline", help="export a chrome://tracing task timeline")
    p.add_argument("--address", required=True)
    p.add_argument("--output", default="ray_tpu_timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("grafana", help="emit an importable Grafana dashboard JSON")
    p.add_argument("-o", "--output", default=None, help="write to file instead of stdout")
    p.set_defaults(fn=cmd_grafana)

    p = sub.add_parser("status", help="nodes + resource totals")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("stacks", help="dump every worker's thread stacks (stuck-worker debugging)")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_stacks)

    p = sub.add_parser(
        "profile",
        help="sampling CPU profile of every worker (collapsed stacks for flamegraph.pl)",
    )
    p.add_argument("--address", required=True)
    p.add_argument("--seconds", type=float, default=2.0)
    p.add_argument("--rate", type=float, default=100.0, help="samples per second")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("node-stats", help="per-node cpu/mem/disk stats")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_nodestats)

    p = sub.add_parser("job", help="submit/inspect jobs on a running cluster")
    p.add_argument("action", choices=["submit", "status", "logs", "stop", "list"])
    p.add_argument("--address", required=True)
    p.add_argument("--job-id")
    p.add_argument("entrypoint", nargs=argparse.REMAINDER,
                   help="(submit) shell command, after --")
    p.set_defaults(fn=cmd_job)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
