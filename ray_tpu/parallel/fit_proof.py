"""GPT-J-6B fit proof: AOT-compile the 6B train step under fsdp and read
XLA's static memory analysis — evidence the north-star model fits a v5e-8
HBM budget without owning 8 chips.

BASELINE.md's reference headline is the GPT-J-6B fine-tune
(``release/air_examples/gptj_deepspeed_finetuning``). This module compiles
the same-shape decoder (vocab 50432, d_model 4096, 28 layers, 16 heads,
seq 2048) through ``build_train_step`` on an 8-device mesh with ZeRO-3
fsdp sharding, using ONLY abstract values (``jax.eval_shape`` +
``ShapeDtypeStruct`` with shardings) — no 6B parameters are ever
materialized, so this runs on a CPU host under
``--xla_force_host_platform_device_count=8``.

``memory_analysis()`` is the per-device XLA estimate: arguments (params +
opt state resident in HBM) + temporaries (activations, collective
buffers) + outputs − donated aliases. v5e HBM is 16 GiB/chip.
"""

from __future__ import annotations

import functools
from typing import Optional


def fit_report(cfg, n_devices: int = 8, batch: int = 8, model: str = "gpt") -> dict:
    """AOT-compile ``cfg``'s train step under fsdp-``n_devices`` from
    abstract values only; return XLA's per-device memory analysis.
    ``model`` picks the architecture: "gpt" (models.gpt) or "gptj" — the
    true GPT-J parallel-block/rotary tree that ``load_hf_gptj`` imports."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import MeshConfig, make_mesh
    from ray_tpu.parallel.sharding import batch_spec, param_sharding_rules
    from ray_tpu.parallel.train_step import TrainState, _opt_shardings, build_train_step

    if model == "gptj":
        from ray_tpu.models.gptj import gptj_init as init_model
        from ray_tpu.models.gptj import gptj_loss

        def model_loss(cfg, params, tokens, mesh):
            return gptj_loss(cfg, params, tokens, mesh)
    else:
        from ray_tpu.models.gpt import gpt_init as init_model
        from ray_tpu.models.gpt import gpt_loss as model_loss

    mesh = make_mesh(MeshConfig(dp=1, fsdp=n_devices, tp=1, sp=1))
    optimizer = optax.adamw(1e-4)

    def loss_fn(params, tokens):
        return model_loss(cfg, params, tokens, mesh)

    _, step_fn = build_train_step(loss_fn, optimizer, mesh)

    # abstract state with the REAL shardings attached — eval_shape never
    # allocates the 24 GB of fp32 master weights
    params_abs = jax.eval_shape(
        functools.partial(init_model, cfg=cfg), jax.random.PRNGKey(0)
    )
    p_specs = param_sharding_rules(params_abs)
    params_sds = jax.tree_util.tree_map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=NamedSharding(mesh, s)),
        params_abs,
        p_specs,
    )
    opt_abs = jax.eval_shape(optimizer.init, params_abs)
    opt_sh = _opt_shardings(optimizer, params_abs, p_specs, mesh)
    opt_sds = jax.tree_util.tree_map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s), opt_abs, opt_sh
    )
    state_abs = TrainState(
        params_sds,
        opt_sds,
        jax.ShapeDtypeStruct((), jnp.int32, sharding=NamedSharding(mesh, P())),
    )
    tokens_abs = jax.ShapeDtypeStruct(
        (batch, cfg.seq_len + 1),
        jnp.int32,
        sharding=NamedSharding(mesh, batch_spec()),
    )

    compiled = step_fn.lower(state_abs, tokens_abs).compile()
    import math

    n_params = sum(math.prod(l.shape) for l in jax.tree_util.tree_leaves(params_abs))
    out = {
        "model_params": n_params,
        "n_devices": n_devices,
        "batch": batch,
        "seq_len": cfg.seq_len,
        "remat_policy": cfg.remat_policy,
        "compiles": True,
    }
    ma = compiled.memory_analysis()
    per_chip: Optional[int] = None
    if ma is not None:
        try:
            args = int(ma.argument_size_in_bytes)
            temps = int(ma.temp_size_in_bytes)
            outs = int(ma.output_size_in_bytes)
            alias = int(ma.alias_size_in_bytes)
            # donated state aliases outputs: resident = args + temps + the
            # non-aliased output tail
            per_chip = args + temps + max(0, outs - alias)
            out.update(
                {
                    "argument_bytes": args,
                    "temp_bytes": temps,
                    "output_bytes": outs,
                    "alias_bytes": alias,
                }
            )
        except AttributeError:
            per_chip = None
    if per_chip is not None:
        out["per_chip_bytes"] = per_chip
        out["per_chip_gib"] = round(per_chip / (1 << 30), 2)
        out["fits_v5e_16gib"] = per_chip < 16 * (1 << 30)
    return out


def gptj_6b_fit_report(
    n_devices: int = 8,
    batch: int = 8,
    remat_policy: str = "full",
    seq_len: int = 2048,
) -> dict:
    """Fit proof of the TRUE GPT-J-6B architecture (models.gptj — the tree
    ``load_hf_gptj`` imports from a real HF checkpoint): rotary, parallel
    residual, no-bias projections, untied biased head."""
    from ray_tpu.models.gptj import GPTJConfig

    cfg = GPTJConfig(
        vocab_size=50_432,  # GPT-J's 50400 padded to the lane multiple
        seq_len=seq_len,
        remat_policy=remat_policy,
    )
    out = fit_report(cfg, n_devices=n_devices, batch=batch, model="gptj")
    out["architecture"] = "gptj"
    return out


def main() -> None:  # pragma: no cover - exercised via bench.py subprocess
    import json
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    print(json.dumps(gptj_6b_fit_report()))


if __name__ == "__main__":  # pragma: no cover
    main()
