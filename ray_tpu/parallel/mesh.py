"""Device-mesh construction.

The reference's unit of scale is "N worker actors, one NCCL rank each"
(``train/_internal/backend_executor.py:358`` sets RANK/WORLD_SIZE). The TPU
unit of scale is a ``jax.sharding.Mesh`` over all chips; this module builds
meshes from either an explicit axis layout or a total device count, factoring
sensibly (tp innermost on ICI neighbors, then fsdp, then dp outermost —
multi-slice dp rides DCN, everything else stays on ICI).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

# Axis order = device-grid nesting, outermost → innermost: tp is the
# fastest-varying axis (adjacent ICI neighbors), then sp, then fsdp, with dp
# outermost (the axis that crosses slice/DCN boundaries). PartitionSpecs refer
# to axes by NAME, so this ordering only affects which physical devices form
# each axis group.
AXES = ("dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass
class MeshConfig:
    """Logical axis sizes. ``-1`` on one axis means "all remaining devices"."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1  # expert parallelism (MoE experts sharded over this axis)

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {
            "dp": self.dp, "fsdp": self.fsdp, "ep": self.ep,
            "tp": self.tp, "sp": self.sp,
        }
        fixed = [a for a, s in sizes.items() if s != -1]
        free = [a for a, s in sizes.items() if s == -1]
        if len(free) > 1:
            raise ValueError("at most one mesh axis may be -1")
        prod = math.prod(sizes[a] for a in fixed)
        if free:
            if n_devices % prod:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[free[0]] = n_devices // prod
        elif prod != n_devices:
            raise ValueError(f"mesh {sizes} needs {prod} devices, have {n_devices}")
        return sizes


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = AXES,
):
    """Build a ``jax.sharding.Mesh``.

    Device order: JAX returns devices in row-major topology order; the AXES
    ordering makes ``tp`` the innermost (fastest-varying) position so
    tensor-parallel collectives ride adjacent ICI links, then ``sp``,
    ``fsdp``, with ``dp`` outermost (the axis that crosses slice/DCN
    boundaries on multi-slice pods).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    import numpy as np

    arr = np.asarray(devices).reshape([sizes[a] for a in axis_names])
    return Mesh(arr, axis_names=tuple(axis_names))


def make_tp_mesh(
    tp: int,
    *,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = ("tp",),
):
    """Build the 1-axis ``("tp",)`` mesh the multi-chip LLM engine runs on.

    A dedicated factory (rather than ``make_mesh(MeshConfig(tp=...))``)
    for two reasons: the serving engine wants the first ``tp`` devices in
    topology order — tensor-parallel collectives every decode step must
    ride adjacent ICI links — and the keyword-only ``axis_names`` default
    keeps the axis tuple statically resolvable for raylint's mesh phase
    (RL020/RL021 resolve ``make_*mesh`` factory defaults; see LINTING.md).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()[:tp]
    if len(devices) != tp:
        raise ValueError(f"tp={tp} needs {tp} devices, have {len(devices)}")
    return Mesh(np.asarray(devices), axis_names=tuple(axis_names))
