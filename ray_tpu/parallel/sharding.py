"""PartitionSpec rule tables for transformer parameter pytrees.

The reference expresses FSDP/ZeRO by wrapping modules
(``train/torch/train_loop_utils.py:176-186``); here sharding is data, not
wrappers: a rule table maps parameter-path regexes to PartitionSpecs, and XLA
SPMD compiles the matching collectives. Conventions (Megatron-style):

* ``tp`` shards the *output* dim of QKV and MLP-in kernels and the *input*
  dim of the attention-proj and MLP-out kernels, so each block needs exactly
  one all-reduce (forward) per sublayer, which XLA fuses into the matmuls.
* ``fsdp`` shards the other (non-tp) dim of every large kernel plus the
  embedding vocab dim — parameters and Adam state live scattered and are
  all-gathered per layer on use (= ZeRO-3).
* activations: batch over ``("dp","fsdp")``, sequence over ``sp``.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

# (regex over '/'-joined param path) -> PartitionSpec
# Matches the GPT pytree in ray_tpu.models.gpt: params are stacked over
# layers (leading scan dim) so specs lead with None for the layer axis.
_RULES = [
    (r"embed/tokens$", P("fsdp", "tp")),          # (vocab, d_model)
    (r"embed/pos$", P(None, None)),               # (seq, d_model)
    (r"blocks/attn_qkv/kernel$", P(None, "fsdp", "tp")),   # (L, d, 3h)
    (r"blocks/attn_qkv/bias$", P(None, "tp")),
    (r"blocks/attn_out/kernel$", P(None, "tp", "fsdp")),   # (L, h, d)
    (r"blocks/attn_out/bias$", P(None, None)),
    (r"blocks/mlp_in/kernel$", P(None, "fsdp", "tp")),     # (L, d, 4d)
    (r"blocks/mlp_in/bias$", P(None, "tp")),
    (r"blocks/mlp_out/kernel$", P(None, "tp", "fsdp")),    # (L, 4d, d)
    (r"blocks/mlp_out/bias$", P(None, None)),
    # MoE: experts over ep, then the usual fsdp/tp split inside each expert
    (r"blocks/router/kernel$", P(None, None, None)),       # (L, d, E) small
    (r"blocks/moe_in/kernel$", P(None, "ep", "fsdp", "tp")),   # (L, E, d, 4d)
    (r"blocks/moe_out/kernel$", P(None, "ep", "tp", "fsdp")),  # (L, E, 4d, d)
    (r"blocks/ln\d/(scale|bias)$", P(None, None)),
    # GPT-J tree (models.gptj): separate no-bias q/k/v, biased lm head
    (r"blocks/[qkv]/kernel$", P(None, "fsdp", "tp")),      # (L, d, d)
    (r"lm_head/bias$", P("fsdp")),                # (vocab,)
    (r"ln_f/(scale|bias)$", P()),  # rank-1 (d,) — replicate
    (r"lm_head/kernel$", P("tp", "fsdp")),        # (d_model, vocab)
]


def spec_for_path(path: str) -> P:
    for pattern, spec in _RULES:
        if re.search(pattern, path):
            return spec
    return P()  # replicate by default (small tensors)


def param_sharding_rules(params: Any) -> Any:
    """Pytree of PartitionSpecs matching ``params``' structure."""

    def one(path, leaf):
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        return spec_for_path(key)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params: Any, mesh) -> Any:
    """device_put the pytree with NamedShardings from the rule table."""
    specs = param_sharding_rules(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def batch_spec() -> P:
    """(batch, seq) token batches: batch sharded over dp+fsdp. The seq dim
    stays UNsharded at the input boundary — token batches carry seq_len+1
    columns (inputs|targets), which sp generally does not divide; the model
    redistributes activations over sp via internal sharding constraints
    (ops/attention ring path), so only the cheap int32 tokens replicate
    within an sp group."""
    return P(("dp", "fsdp"))


def constrain(x, mesh, spec: P):
    """with_sharding_constraint pinned to a mesh (no-op outside jit)."""
    from jax.lax import with_sharding_constraint

    return with_sharding_constraint(x, NamedSharding(mesh, spec))
