"""Multi-slice meshes: data parallelism over DCN, everything else on ICI.

SURVEY §7 names 8→256-chip scaling via DCN-overlapped gradient reduction as
make-or-break. The reference scales across hosts with NCCL rings over the
datacenter network; the TPU-native design is a HYBRID device mesh
(reference mental model: the scaling-book's multi-slice recipe, and jax's
``mesh_utils.create_hybrid_device_mesh``):

* within a slice, devices are ordered so tp/sp/fsdp collectives ride
  adjacent ICI links (same nesting as ``parallel.mesh.AXES``);
* the ``dp`` axis is SLICE-MAJOR: its groups pair corresponding chips of
  different slices, so data-parallel gradient reduction is the only
  traffic that crosses DCN.

No new axis name is introduced — the model/sharding code is unchanged.
GSPMD decomposes the dp all-reduce hierarchically over the hybrid ordering
(reduce-scatter on ICI → cross-slice exchange on DCN → all-gather on ICI),
and XLA's latency-hiding scheduler overlaps the DCN phase with ICI compute
of neighbouring layers — the overlap SURVEY §7 asks for comes from the
compiler, not hand-written schedules.

Real multi-slice pods are detected through ``device.slice_index`` (set by
the TPU runtime); anywhere else (CPU dryruns, single slice) the devices are
partitioned into ``num_slices`` contiguous groups, which preserves the
slice-major dp semantics for compile-and-execute validation on a virtual
mesh (``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import Optional, Sequence

import numpy as np

from ray_tpu.parallel.mesh import AXES, MeshConfig


def slice_groups(devices: Sequence, num_slices: Optional[int] = None) -> list[list]:
    """Partition devices into slices: by the runtime's ``slice_index`` when
    present, else into ``num_slices`` contiguous groups."""
    by_idx: dict[int, list] = {}
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        for d in devices:
            by_idx.setdefault(d.slice_index, []).append(d)
        groups = [by_idx[i] for i in sorted(by_idx)]
        if num_slices is None or len(groups) == num_slices:
            return groups
        if len(groups) > 1:
            # asking to re-partition across REAL slice boundaries would put
            # ICI axes over DCN — reject; simulation is only meaningful on
            # a single physical slice (or CPU)
            raise ValueError(
                f"hardware reports {len(groups)} slices, requested {num_slices}"
            )
        # single physical slice + explicit num_slices: fall through to the
        # simulated contiguous partitioning (compile-and-execute validation)
    if num_slices is None:
        return [list(devices)]
    if num_slices <= 0:
        raise ValueError(f"num_slices must be positive, got {num_slices}")
    n = len(devices)
    if n % num_slices:
        raise ValueError(f"{n} devices not divisible into {num_slices} slices")
    per = n // num_slices
    return [list(devices[i * per : (i + 1) * per]) for i in range(num_slices)]


def make_multislice_mesh(
    config: Optional[MeshConfig] = None,
    *,
    num_slices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = AXES,
):
    """Build a hybrid mesh whose dp axis crosses slices (DCN) while the
    remaining axes stay within a slice (ICI).

    ``config`` sizes are TOTALS (like ``make_mesh``); dp must be a multiple
    of the slice count — each slice contributes ``dp // num_slices`` local
    dp groups, and dp's MAJOR dimension enumerates slices.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    groups = slice_groups(devices, num_slices)
    s = len(groups)
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    if sizes["dp"] % s:
        raise ValueError(
            f"dp={sizes['dp']} must be a multiple of the slice count {s} "
            f"(data parallelism is the axis that crosses DCN)"
        )
    non_dp = [a for a in axis_names if a != "dp"]
    per_slice_shape = [sizes["dp"] // s] + [sizes[a] for a in non_dp]
    # (slice, dp_local, rest...) → merge (slice, dp_local) into slice-major dp
    arr = np.stack(
        [np.asarray(g).reshape(per_slice_shape) for g in groups], axis=0
    ).reshape([sizes["dp"]] + per_slice_shape[1:])
    # restore the caller's axis order (dp first in AXES already)
    order = ["dp"] + non_dp
    perm = [order.index(a) for a in axis_names]
    arr = np.transpose(arr, perm)
    return Mesh(arr, axis_names=tuple(axis_names))


def launch_multislice_procs(
    num_procs: int = 2,
    local_devices: int = 4,
    steps: int = 2,
    timeout: float = 600.0,
) -> list[list[float]]:
    """Run the REAL multi-process multislice dryrun: ``num_procs`` fresh
    subprocesses, each ``jax.distributed.initialize``-ing into one shared
    runtime with ``local_devices`` virtual CPU chips, training the tiny GPT
    over a single global mesh whose dp axis crosses the process boundary
    (``_multislice_worker.py``; reference counterpart: the cross-host torch
    process group in ``python/ray/train/torch/config.py:47-91``).

    Returns per-rank loss trajectories (all ranks must agree bit-for-bit:
    the update is a deterministic function of replicated inputs, so
    agreement proves the cross-process collective ran correctly).
    """
    # the free-port probe is TOCTOU (another process can claim it between
    # close and the coordinator's bind): retry the whole launch on a fresh
    # port when the failure smells like a bind clash
    last_err: Optional[BaseException] = None
    for _attempt in range(3):
        try:
            return _launch_once(num_procs, local_devices, steps, timeout)
        except RuntimeError as e:
            msg = str(e).lower()
            if "bind" in msg or "address" in msg or "in use" in msg:
                last_err = e
                continue
            raise
    raise last_err  # type: ignore[misc]


def _launch_once(
    num_procs: int, local_devices: int, steps: int, timeout: float
) -> list[list[float]]:
    import tempfile
    import time as _time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    # output to files, not pipes: a crashed rank's log must survive the
    # kill path, and pipes deadlock if a worker fills one while we block
    # on a sibling's communicate()
    logs = [tempfile.NamedTemporaryFile("w+", suffix=f".ms{r}.log") for r in range(num_procs)]
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu.parallel._multislice_worker",
                "--rank", str(r), "--coord", coord,
                "--procs", str(num_procs),
                "--local-devices", str(local_devices),
                "--steps", str(steps),
            ],
            env=env,
            stdout=logs[r],
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(num_procs)
    ]

    def read_log(r: int) -> str:
        logs[r].flush()
        logs[r].seek(0)
        return logs[r].read()

    try:
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            rcs = [p.poll() for p in procs]
            # any rank dying early would leave the others waiting in
            # distributed barriers until the full timeout: fail fast with
            # the crashed rank's log (the informative one)
            if any(rc is not None and rc != 0 for rc in rcs):
                bad = next(r for r, rc in enumerate(rcs) if rc not in (None, 0))
                raise RuntimeError(
                    f"multislice worker rank {bad} failed "
                    f"(rc={rcs[bad]}):\n{read_log(bad)[-4000:]}"
                )
            if all(rc == 0 for rc in rcs):
                break
            _time.sleep(0.2)
        else:
            raise RuntimeError(
                "multislice dryrun timed out; rank logs:\n"
                + "\n---\n".join(read_log(r)[-2000:] for r in range(num_procs))
            )
        outs = [read_log(r) for r in range(num_procs)]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    losses: list[list[float]] = [None] * num_procs  # type: ignore[list-item]
    for p, out in zip(procs, outs):
        for line in out.splitlines():
            if line.startswith("MSPROC rank="):
                rank = int(line.split("rank=")[1].split()[0])
                losses[rank] = eval(line.split("losses=")[1])  # noqa: S307 - our own output
    if any(l is None for l in losses):
        raise RuntimeError(f"missing MSPROC lines in worker output:\n{outs}")
    for r in range(1, num_procs):
        if losses[r] != losses[0]:
            raise RuntimeError(
                f"rank {r} diverged from rank 0: {losses[r]} vs {losses[0]} — "
                "cross-process collective inconsistency"
            )
    return losses
