"""Multi-slice meshes: data parallelism over DCN, everything else on ICI.

SURVEY §7 names 8→256-chip scaling via DCN-overlapped gradient reduction as
make-or-break. The reference scales across hosts with NCCL rings over the
datacenter network; the TPU-native design is a HYBRID device mesh
(reference mental model: the scaling-book's multi-slice recipe, and jax's
``mesh_utils.create_hybrid_device_mesh``):

* within a slice, devices are ordered so tp/sp/fsdp collectives ride
  adjacent ICI links (same nesting as ``parallel.mesh.AXES``);
* the ``dp`` axis is SLICE-MAJOR: its groups pair corresponding chips of
  different slices, so data-parallel gradient reduction is the only
  traffic that crosses DCN.

No new axis name is introduced — the model/sharding code is unchanged.
GSPMD decomposes the dp all-reduce hierarchically over the hybrid ordering
(reduce-scatter on ICI → cross-slice exchange on DCN → all-gather on ICI),
and XLA's latency-hiding scheduler overlaps the DCN phase with ICI compute
of neighbouring layers — the overlap SURVEY §7 asks for comes from the
compiler, not hand-written schedules.

Real multi-slice pods are detected through ``device.slice_index`` (set by
the TPU runtime); anywhere else (CPU dryruns, single slice) the devices are
partitioned into ``num_slices`` contiguous groups, which preserves the
slice-major dp semantics for compile-and-execute validation on a virtual
mesh (``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ray_tpu.parallel.mesh import AXES, MeshConfig


def slice_groups(devices: Sequence, num_slices: Optional[int] = None) -> list[list]:
    """Partition devices into slices: by the runtime's ``slice_index`` when
    present, else into ``num_slices`` contiguous groups."""
    by_idx: dict[int, list] = {}
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        for d in devices:
            by_idx.setdefault(d.slice_index, []).append(d)
        groups = [by_idx[i] for i in sorted(by_idx)]
        if num_slices is None or len(groups) == num_slices:
            return groups
        if len(groups) > 1:
            # asking to re-partition across REAL slice boundaries would put
            # ICI axes over DCN — reject; simulation is only meaningful on
            # a single physical slice (or CPU)
            raise ValueError(
                f"hardware reports {len(groups)} slices, requested {num_slices}"
            )
        # single physical slice + explicit num_slices: fall through to the
        # simulated contiguous partitioning (compile-and-execute validation)
    if num_slices is None:
        return [list(devices)]
    if num_slices <= 0:
        raise ValueError(f"num_slices must be positive, got {num_slices}")
    n = len(devices)
    if n % num_slices:
        raise ValueError(f"{n} devices not divisible into {num_slices} slices")
    per = n // num_slices
    return [list(devices[i * per : (i + 1) * per]) for i in range(num_slices)]


def make_multislice_mesh(
    config: Optional[MeshConfig] = None,
    *,
    num_slices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis_names: Sequence[str] = AXES,
):
    """Build a hybrid mesh whose dp axis crosses slices (DCN) while the
    remaining axes stay within a slice (ICI).

    ``config`` sizes are TOTALS (like ``make_mesh``); dp must be a multiple
    of the slice count — each slice contributes ``dp // num_slices`` local
    dp groups, and dp's MAJOR dimension enumerates slices.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    groups = slice_groups(devices, num_slices)
    s = len(groups)
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    if sizes["dp"] % s:
        raise ValueError(
            f"dp={sizes['dp']} must be a multiple of the slice count {s} "
            f"(data parallelism is the axis that crosses DCN)"
        )
    non_dp = [a for a in axis_names if a != "dp"]
    per_slice_shape = [sizes["dp"] // s] + [sizes[a] for a in non_dp]
    # (slice, dp_local, rest...) → merge (slice, dp_local) into slice-major dp
    arr = np.stack(
        [np.asarray(g).reshape(per_slice_shape) for g in groups], axis=0
    ).reshape([sizes["dp"]] + per_slice_shape[1:])
    # restore the caller's axis order (dp first in AXES already)
    order = ["dp"] + non_dp
    perm = [order.index(a) for a in axis_names]
    arr = np.transpose(arr, perm)
    return Mesh(arr, axis_names=tuple(axis_names))
