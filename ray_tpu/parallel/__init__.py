"""SPMD parallelism over TPU device meshes.

This package is the TPU-native replacement for the parallelism the reference
delegates to torch.distributed/NCCL/DeepSpeed inside ``train_loop_per_worker``
(reference: ``python/ray/train/torch/train_loop_utils.py:158-186`` DDP/FSDP
wrapping, ``train/torch/config.py:47-91`` process-group setup):

* data parallel (DDP)        → ``dp`` mesh axis; gradients reduced by XLA
  collectives over ICI during the compiled step, no wrapper object.
* sharded data parallel (ZeRO/FSDP) → ``fsdp`` axis; parameters and optimizer
  state sharded with NamedSharding, all-gathered per layer by XLA.
* tensor parallel (Megatron) → ``tp`` axis on weight matrices.
* sequence/context parallel  → ``sp`` axis on the sequence dimension of
  activations (ring attention in ``ray_tpu.ops``).

Everything is driven by one ``Mesh`` + PartitionSpec rule table; XLA SPMD
inserts the all-reduce / all-gather / reduce-scatter collectives.
"""

from ray_tpu.parallel.mesh import MeshConfig, make_mesh  # noqa: F401
from ray_tpu.parallel.sharding import (  # noqa: F401
    batch_spec,
    constrain,
    param_sharding_rules,
    shard_params,
)
from ray_tpu.parallel.train_step import TrainState, build_train_step  # noqa: F401
