"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style).

SURVEY §2.4 lists PP as absent from the reference ("expressible as actor
pipelines / compiled DAG channels", never implemented natively). The
TPU-first realization is NOT an actor pipeline: all ``pp`` stages live in
one pjit program; layer parameters shard over the ``pp`` axis (stage s holds
layers [s·L/pp, (s+1)·L/pp)); microbatches stream through a ``lax.scan``
over ticks where every stage processes its resident microbatch and hands
activations to its successor via ``lax.ppermute`` — the collective-permute
pipeline used by production TPU frameworks. The schedule is GPipe: M
microbatches drain in M + pp − 1 ticks (bubble fraction (pp−1)/(M+pp−1)),
and reverse-mode AD through scan+ppermute yields the backward pipeline
automatically.

``pipeline_apply`` is model-agnostic: any ``stage_fn(stage_params, x) -> y``
whose stacked parameters carry a leading layer dimension works.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _stage_slice(tree: Any, stage: jax.Array, n_stages: int, n_layers: int):
    """Dynamic-slice each stacked param (L, ...) to this stage's (L/pp, ...)."""
    per = n_layers // n_stages

    def one(leaf):
        start = (stage * per,) + (0,) * (leaf.ndim - 1)
        sizes = (per,) + leaf.shape[1:]
        return jax.lax.dynamic_slice(leaf, start, sizes)

    return jax.tree_util.tree_map(one, tree)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh,
    n_layers: int,
    microbatches: int,
    axis_name: str = "pp",
    batch_axes: tuple = (("dp", "fsdp"),),
):
    """Run ``x`` (batch, ...) through ``n_layers`` stacked layers pipelined
    over the mesh's ``pp`` axis with GPipe microbatching.

    - ``stage_fn(stage_params, x_mb)`` applies ONE stage's layers to one
      microbatch (it typically scans its local layers).
    - ``stacked_params``: pytree with leading layer dim L (sharded over pp by
      the caller's param shardings).
    - ``microbatches`` must divide the (global) batch.

    Returns activations with the same shape/sharding as ``x``.
    """
    pp = mesh.shape.get(axis_name, 1)
    if pp == 1:
        return stage_fn(stacked_params, x)
    if n_layers % pp:
        raise ValueError(f"n_layers {n_layers} must divide by pp={pp}")

    in_spec = P(*batch_axes) if batch_axes else P()
    # params enter shard_map split over pp on the LAYER dim
    param_spec = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)

    def shard_body(params_local, x_local):
        # params_local: (L/pp, ...) this stage's layers; x_local: local batch
        stage = jax.lax.axis_index(axis_name)
        b = x_local.shape[0]
        if b % microbatches:
            raise ValueError(
                f"local batch {b} must divide into microbatches={microbatches}"
            )
        mb = b // microbatches
        xs = x_local.reshape((microbatches, mb) + x_local.shape[1:])
        n_ticks = microbatches + pp - 1
        # pad the microbatch stream with zeros for drain ticks
        pad = jnp.zeros((pp - 1,) + xs.shape[1:], xs.dtype)
        feed = jnp.concatenate([xs, pad], axis=0)

        def tick(carry, x_t):
            incoming = carry  # activations arriving from the previous stage
            x_in = jnp.where(stage == 0, x_t, incoming)
            y = stage_fn(params_local, x_in)
            # hand off to the next stage (stage pp-1's output falls off the
            # end — it is the pipeline's OUTPUT, collected in ys)
            passed = jax.lax.ppermute(
                y, axis_name, [(i, i + 1) for i in range(pp - 1)]
            )
            return passed, y

        _, ys = jax.lax.scan(tick, jnp.zeros_like(feed[0]), feed)
        # stage pp-1 emitted microbatch m at tick m + pp - 1; every stage
        # computes the same gather, but only the LAST stage's ys hold real
        # outputs — broadcast them back around the ring so every stage
        # returns identical activations (keeps downstream ops replicated
        # over pp, like the reference's last-stage-owns-loss designs avoid).
        out = ys[pp - 1 :]  # (microbatches, mb, ...)
        out = out.reshape((b,) + x_local.shape[1:])
        # broadcast the last stage's (only real) output to every stage:
        # mask+psum — one collective, keeps downstream ops replicated over pp
        out = jnp.where(stage == pp - 1, out, jnp.zeros_like(out))
        out = jax.lax.psum(out, axis_name)
        return out

    from ray_tpu._private.jax_compat import shard_map

    fn = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(param_spec, in_spec),
        out_specs=in_spec,
        check_vma=False,
    )
    return fn(stacked_params, x)
