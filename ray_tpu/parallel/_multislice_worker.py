"""Subprocess body for the REAL multi-process multislice dryrun.

Each worker is one "slice host": it owns ``--local-devices`` virtual CPU
chips, joins the global runtime via ``jax.distributed.initialize`` (the
TPU-native counterpart of the reference building a cross-host process group
in ``python/ray/train/torch/config.py:47-91``), and participates in ONE
global mesh whose dp axis crosses the process boundary — so the dp gradient
all-reduce really rides the inter-process (DCN-equivalent) channel, here
gloo over localhost, on real pods the megascale DCN transport.

Run via ``ray_tpu.parallel.multislice.launch_multislice_procs`` (or by hand:
``python -m ray_tpu.parallel._multislice_worker --rank 0 --coord
localhost:PORT --procs 2``).
"""

from __future__ import annotations

import argparse
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--coord", required=True)
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=4)
    ap.add_argument("--steps", type=int, default=2)
    args = ap.parse_args()

    # Must precede the first jax import in this (fresh) process.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.local_devices}"
    )
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", args.local_devices)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=args.coord,
        num_processes=args.procs,
        process_id=args.rank,
    )

    import numpy as np
    import optax
    from jax.sharding import NamedSharding

    from ray_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss
    from ray_tpu.parallel.mesh import MeshConfig
    from ray_tpu.parallel.multislice import make_multislice_mesh
    from ray_tpu.parallel.sharding import batch_spec
    from ray_tpu.parallel.train_step import build_train_step, global_put

    n_global = args.procs * args.local_devices
    assert len(jax.devices()) == n_global, (len(jax.devices()), n_global)
    # jax.devices() is process-major, so contiguous slice partitioning puts
    # the slice boundary exactly on the process boundary: dp's major dim
    # enumerates processes, tp stays within one process ("ICI").
    tp = 2 if args.local_devices % 2 == 0 else 1
    mesh = make_multislice_mesh(
        MeshConfig(dp=n_global // tp, fsdp=1, tp=tp, sp=1),
        num_slices=args.procs,
        devices=jax.devices(),
    )

    cfg = GPTConfig(
        vocab_size=512, seq_len=64, d_model=128, n_layers=2, n_heads=4
    )

    def loss_fn(params, batch):
        return gpt_loss(cfg, params, batch, mesh)

    init_fn, step_fn = build_train_step(loss_fn, optax.adamw(1e-3), mesh)

    with jax.default_device(jax.local_devices()[0]):
        params = gpt_init(jax.random.PRNGKey(0), cfg)  # same seed every rank
        state = init_fn(params)
        rng = np.random.default_rng(0)  # same batch every rank
        batch_host = rng.integers(
            0, cfg.vocab_size, size=(n_global // tp * 2, cfg.seq_len + 1)
        ).astype(np.int32)
        batch = global_put(batch_host, NamedSharding(mesh, batch_spec()))
        losses = []
        for _ in range(args.steps):
            state, loss = step_fn(state, batch)
            losses.append(float(loss))  # replicated scalar: addressable everywhere
    assert all(np.isfinite(l) and l > 0 for l in losses), losses
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    print(f"MSPROC rank={args.rank} losses={losses}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
