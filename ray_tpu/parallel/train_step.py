"""Compiled SPMD training step.

The reference's training step is user torch code with DDP allreduce hooks
(``train/torch/train_loop_utils.py:158``); ours is one jitted function over
the mesh: forward + backward + optimizer update, with gradient reduction,
ZeRO gathers and tensor-parallel collectives all compiled by XLA SPMD from
the sharding annotations. Optimizer state inherits the parameter shardings
(ZeRO: Adam moments live scattered over ``fsdp``).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import optax
from jax.sharding import NamedSharding

from ray_tpu.parallel.sharding import batch_spec, param_sharding_rules


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def global_put(x, sharding) -> jax.Array:
    """Place a host array onto a (possibly multi-process) sharding.

    ``jax.device_put`` rejects shardings that span non-addressable devices;
    ``make_array_from_callback`` builds the global array from the shards this
    process owns, so the SAME init path serves the single-process virtual
    mesh and the real two-process DCN dryrun (every process must hold the
    same host value — true for seeded param init and test batches)."""
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)  # no host round-trip single-process
    import numpy as np

    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def shard_train_state(params, p_specs, optimizer, mesh) -> TrainState:
    """Place a host param tree onto the mesh per ``p_specs`` and build the
    matching sharded optimizer state (shared by build_train_step and the
    flax bridge — ONE copy of the ZeRO placement wiring)."""
    params = jax.tree_util.tree_map(
        lambda x, s: global_put(x, NamedSharding(mesh, s)), params, p_specs
    )
    opt_state = jax.jit(
        optimizer.init,
        out_shardings=_opt_shardings(optimizer, params, p_specs, mesh),
    )(params)
    import numpy as np
    from jax.sharding import PartitionSpec as P

    # the step counter is placed REPLICATED ON THE MESH like every other
    # state leaf: a bare jnp.zeros(()) carries SingleDeviceSharding, which
    # differs from the step output's NamedSharding — the jitted train step
    # then silently RETRACED (full fwd+bwd recompile) on its second call
    # (found by util.device_prof's retrace detector)
    step0 = global_put(np.zeros((), np.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step0)


def make_step_fn(loss_fn, optimizer, mesh):
    """Jitted fwd+bwd+optimizer step with donated state and dp/fsdp-sharded
    batches (the step half of ``build_train_step``, reusable with any
    param-sharding source)."""

    def step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(
        step,
        in_shardings=(None, NamedSharding(mesh, batch_spec())),
        donate_argnums=(0,),
    )


def profile_step_fn(step_fn, site: str = "train_step"):
    """Opt-in device-step profiling for a jitted train step: wall time
    per call into ``device_step_seconds{site=train_step}`` and runtime
    retrace detection (``train.retrace`` events + the ``device_retraces``
    counter feeding the retrace-storm SLO — ``util.device_prof``).

    A WRAPPER on purpose: ``make_step_fn``'s return stays a bare
    ``jax.jit`` call so raylint's dataflow summaries keep resolving its
    ``donate_argnums`` for use-after-donation analysis at call sites.
    The wrapped callable exposes ``.profiler`` (per-site stats) and
    ``.__wrapped__`` (the raw jitted step)."""
    import time

    from ray_tpu.util.device_prof import JitProfiler

    prof = JitProfiler(event="train.retrace")

    def profiled(state, batch):
        t0 = time.perf_counter()
        out = step_fn(state, batch)
        prof.note(site, step_fn, time.perf_counter() - t0)
        return out

    profiled.profiler = prof
    profiled.__wrapped__ = step_fn
    return profiled


def build_train_step(
    loss_fn: Callable[[Any, jax.Array], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh,
):
    """Returns ``(init_fn, step_fn)``.

    ``loss_fn(params, batch) -> scalar`` is differentiated; ``init_fn(params)``
    shards params + optimizer state onto the mesh; ``step_fn(state, batch)``
    is jitted with explicit in/out shardings so it can be dispatched with zero
    host-side resharding.
    """

    def init_fn(params) -> TrainState:
        return shard_train_state(params, param_sharding_rules(params), optimizer, mesh)

    return init_fn, make_step_fn(loss_fn, optimizer, mesh)


def _opt_shardings(optimizer, params, p_specs, mesh):
    """Optimizer-state shardings: optax state subtrees (Adam mu/nu, …) mirror
    the parameter pytree, so an opt-state leaf path ends with some parameter's
    path — match by longest path suffix and inherit that param's spec (ZeRO:
    moments live scattered exactly like their parameter). Non-mirroring leaves
    (step counts, scalars) replicate."""
    from jax.sharding import PartitionSpec as P

    def path_keys(path):
        return tuple(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)

    param_specs: dict[tuple, Any] = {}
    jax.tree_util.tree_map_with_path(
        lambda path, spec: param_specs.setdefault(path_keys(path), spec), p_specs
    )

    shapes = jax.eval_shape(optimizer.init, params)

    def one(path, leaf):
        keys = path_keys(path)
        for i in range(len(keys)):
            spec = param_specs.get(keys[i:])
            if spec is not None and len(spec) <= len(leaf.shape):
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(one, shapes)
