"""In-process multi-node test cluster.

Counterpart of the reference's ``python/ray/cluster_utils.py:108`` —
``Cluster().add_node(resources)`` registers extra virtual nodes against the
same head (the reference starts extra raylet processes; we register extra
NodeStates whose worker pools are real separate processes). This is the
workhorse fixture for scheduling, placement-group and fault-tolerance tests,
including ``remove_node`` as the node-kill fault injection.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ray_tpu._private import api as _api
from ray_tpu._private.head import Head
from ray_tpu._private.ids import NodeID


_CLUSTERS: dict[str, "Cluster"] = {}  # address -> cluster, for init(address=...)


def resolve_address(address: str) -> "Cluster":
    c = _CLUSTERS.get(address)
    if c is None:
        raise ValueError(f"Unknown cluster address {address!r}")
    return c


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
        head: Optional[Head] = None,
    ):
        """``head=`` wraps an ALREADY-RUNNING head (e.g. the one
        ``ray_tpu up`` hosts) instead of creating a private one — virtual
        nodes then register against the live cluster."""
        self._owns_head = head is None
        if head is not None:
            self.head = head
            self.nodes: list[NodeID] = []
            self.head_node: Optional[NodeID] = None
            return
        self._session_dir = tempfile.mkdtemp(prefix="ray_tpu_cluster_")
        sock = os.path.join(self._session_dir, "head.sock")
        self.head = Head(sock, authkey=os.urandom(16))
        self.head.start()
        self.nodes = []
        self.head_node = None
        if initialize_head:
            args = dict(head_node_args or {})
            self.head_node = self.add_node(**args)

    def add_node(
        self,
        num_cpus: int = 1,
        num_tpus: int = 0,
        num_gpus: int = 0,
        resources: Optional[dict] = None,
        labels: Optional[dict] = None,
        **kwargs,
    ) -> NodeID:
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res.setdefault("TPU", float(num_tpus))
        if num_gpus:
            res.setdefault("GPU", float(num_gpus))
        node_id = self.head.add_node(res, labels=labels)
        self.nodes.append(node_id)
        return node_id

    @property
    def address(self) -> str:
        """Opaque attach address (reference: ``cluster.address`` passed to
        ``ray.init(address=...)``)."""
        addr = f"ray-tpu://{id(self):x}"
        _CLUSTERS[addr] = self
        return addr

    def remove_node(self, node_id: NodeID, allow_graceful: bool = True) -> None:
        """Simulated node failure (reference: cluster.remove_node /
        NodeKillerActor)."""
        self.head.remove_node(node_id)
        if node_id in self.nodes:
            self.nodes.remove(node_id)

    def connect(self):
        """Attach a driver to this cluster (reference: ray.init(address=cluster.address))."""
        if self.head_node is None:
            raise RuntimeError("Cluster has no head node")
        return _api.init(_head=self.head, _node_id=self.head_node)

    def shutdown(self):
        _api.shutdown()
        if not self._owns_head:
            return  # a borrowed head (ray_tpu up) outlives this wrapper
        try:
            self.head.shutdown()
        except Exception:
            pass

    def wait_for_nodes(self, timeout: float = 30.0):
        return True
