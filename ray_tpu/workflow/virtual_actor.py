"""Virtual actors: durable named entities whose method calls are
storage-backed transactions.

Reference: ``python/ray/workflow/`` virtual actors (``workflow.get_actor``
/ ``@workflow.virtual_actor``) — an "actor" that outlives any process:
its state lives in workflow storage, each method call loads the state,
runs the method as a cluster task, and atomically commits (new state,
result). A crashed caller re-issues the call; a committed call never
re-runs (calls are keyed, like workflow steps).

Per-actor sequential consistency: with a cluster attached, transactions
serialize on a HEAD-SIDE named mutex (``rpc_mutex_acquire`` — leased, so
a crashed holder recovers instead of wedging the actor; works no matter
where the storage directory lives, including NFS/cloud mounts where file
locks degrade to advisory). Without a cluster the fcntl file lock remains
as the single-host fallback. Methods marked ``@readonly`` skip the commit
and the lock entirely.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import pickle
import time
from typing import Any, Optional

import ray_tpu

_DEFAULT_STORAGE = os.path.expanduser("~/ray_tpu_workflows")


def readonly(method):
    """Mark a virtual-actor method as state-free: no commit, no write lock."""
    method.__workflow_readonly__ = True
    return method


@ray_tpu.remote
def _apply_method(cls_blob: bytes, state: dict, method_name: str, args, kwargs):
    """Run one actor method on the cluster: rebuild the instance from its
    durable state, apply, return (result, new state)."""
    import cloudpickle

    cls = cloudpickle.loads(cls_blob)
    obj = cls.__new__(cls)
    obj.__dict__.update(state)
    result = getattr(obj, method_name)(*args, **kwargs)
    return result, dict(obj.__dict__)


class VirtualActorHandle:
    def __init__(
        self, actor_cls, actor_id: str, storage: str, txn_lease_s: float = 300.0
    ):
        self._cls = actor_cls
        self._id = actor_id
        self._dir = os.path.join(storage, "virtual_actors", actor_id)
        self._blob: Optional[bytes] = None
        self._lease_s = float(txn_lease_s)

    # -- storage ------------------------------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self._dir, "state.pkl")

    @contextlib.contextmanager
    def _file_lock(self):
        with open(os.path.join(self._dir, ".lock"), "w") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)

    def _mutex_key(self) -> str:
        """Storage-INDEPENDENT mutex identity: a UUID persisted inside the
        actor directory (O_EXCL creation — first writer wins, racers read).
        Two hosts mounting the same storage at different paths therefore
        contend on the same head mutex; a path-derived name would not."""
        path = os.path.join(self._dir, ".mutex_id")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                f.write(os.urandom(16).hex())
        except FileExistsError:
            pass
        with open(path) as f:
            return f"va:{f.read().strip()}"

    @contextlib.contextmanager
    def _txn_lock(self):
        """Yields a ``verify()`` callable the write path MUST call before
        committing: it re-asserts mutex ownership (same-owner acquire
        renews; returns False if the lease expired and someone stole it),
        turning a silently lost update into a loud error."""
        os.makedirs(self._dir, exist_ok=True)
        if not ray_tpu.is_initialized():
            with self._file_lock():
                yield lambda: True
            return
        # Head-side named mutex: correct across hosts and storage backends
        # (identity from _mutex_key, not the caller's local path); the
        # lease (txn_lease_s) bounds crashed-holder recovery. The local
        # file lock is held AS WELL, so a clusterless process on the same
        # host still mutually excludes.
        from ray_tpu._private.runtime import get_ctx

        ctx = get_ctx()
        name = self._mutex_key()
        owner = os.urandom(8).hex()
        ctx.call(
            "mutex_acquire", name=name, owner=owner, lease_s=self._lease_s
        )

        def verify() -> bool:
            return bool(
                ctx.call(
                    "mutex_acquire",
                    name=name,
                    owner=owner,
                    timeout=0,
                    lease_s=self._lease_s,
                )
            )

        try:
            with self._file_lock():
                yield verify
        finally:
            try:
                ctx.call("mutex_release", name=name, owner=owner)
            except Exception:
                pass  # lease expiry reclaims it

    def _load_state(self) -> dict:
        with open(self._state_path(), "rb") as f:
            return pickle.load(f)

    def _commit(self, state: dict, method: str) -> None:
        tmp = self._state_path() + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, self._state_path())  # atomic
        with open(os.path.join(self._dir, "log.jsonl"), "a") as f:
            import json

            f.write(json.dumps({"method": method, "time": time.time()}) + "\n")

    def _class_blob(self) -> bytes:
        if self._blob is None:
            import cloudpickle

            self._blob = cloudpickle.dumps(self._cls)
        return self._blob

    # -- calls --------------------------------------------------------------

    def exists(self) -> bool:
        return os.path.exists(self._state_path())

    def _init(self, args, kwargs) -> None:
        with self._txn_lock() as verify:
            if self.exists():
                return  # get_or_create: an existing actor keeps its state
            obj = self._cls(*args, **kwargs)
            if not verify():
                raise RuntimeError(
                    f"virtual actor {self._id!r}: transaction lease expired "
                    f"before commit (raise txn_lease_s for slow __init__)"
                )
            self._commit(dict(obj.__dict__), "__init__")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        method = getattr(self._cls, name, None)
        if method is None or not callable(method):
            raise AttributeError(f"{self._cls.__name__} has no method {name!r}")
        is_readonly = getattr(method, "__workflow_readonly__", False)

        def call(*args, **kwargs):
            if is_readonly:
                state = self._load_state()
                result, _ = ray_tpu.get(
                    _apply_method.remote(self._class_blob(), state, name, args, kwargs)
                )
                return result
            with self._txn_lock() as verify:  # serialize read-modify-write
                state = self._load_state()
                result, new_state = ray_tpu.get(
                    _apply_method.remote(self._class_blob(), state, name, args, kwargs)
                )
                if not verify():
                    # the lease expired mid-transaction and another writer
                    # took over: committing now would silently overwrite its
                    # update — fail loudly instead (reference semantics: a
                    # lost transaction is retried by the caller)
                    raise RuntimeError(
                        f"virtual actor {self._id!r}: transaction exceeded "
                        f"its lease ({self._lease_s}s) and lost the mutex; "
                        f"retry, or pass txn_lease_s= for long methods"
                    )
                self._commit(new_state, name)
            return result

        return call


class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls

    def get_or_create(
        self,
        actor_id: str,
        *args,
        storage: Optional[str] = None,
        txn_lease_s: float = 300.0,
        **kwargs,
    ) -> VirtualActorHandle:
        handle = VirtualActorHandle(
            self._cls, actor_id, storage or _DEFAULT_STORAGE, txn_lease_s
        )
        handle._init(args, kwargs)
        return handle

    def get(
        self,
        actor_id: str,
        storage: Optional[str] = None,
        txn_lease_s: float = 300.0,
    ) -> VirtualActorHandle:
        handle = VirtualActorHandle(
            self._cls, actor_id, storage or _DEFAULT_STORAGE, txn_lease_s
        )
        if not handle.exists():
            raise ValueError(f"virtual actor {actor_id!r} does not exist")
        return handle


def virtual_actor(cls) -> VirtualActorClass:
    """Class decorator: ``@workflow.virtual_actor`` (reference name)."""
    return VirtualActorClass(cls)


def get_actor(
    actor_id: str,
    cls,
    storage: Optional[str] = None,
    txn_lease_s: float = 300.0,
) -> VirtualActorHandle:
    """Attach to an existing virtual actor (reference: workflow.get_actor;
    the class travels with the caller here — no cluster-global class
    registry in the lite design)."""
    inner = cls._cls if isinstance(cls, VirtualActorClass) else cls
    return VirtualActorClass(inner).get(
        actor_id, storage=storage, txn_lease_s=txn_lease_s
    )
