"""ray_tpu.workflow: durable DAG execution with storage-backed checkpoints.

Reference: ``python/ray/workflow/`` (``workflow_executor.py`` step loop +
``workflow_storage.py`` persisted step results). A workflow is a
``ray_tpu.dag`` graph executed step-by-step with every completed step's
result persisted; re-running (or ``resume``-ing after a crash) skips steps
whose results already exist on storage, so a workflow survives driver death
at the granularity of one step.

    from ray_tpu import workflow

    dag = train.bind(prepare.bind(cfg))
    out = workflow.run(dag, workflow_id="exp1", storage="/data/wf")
    # crash anywhere -> workflow.resume("exp1", storage="/data/wf")
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode, MultiOutputNode
from ray_tpu.workflow.virtual_actor import (  # noqa: F401
    get_actor,
    readonly,
    virtual_actor,
)

_DEFAULT_STORAGE = os.path.expanduser("~/ray_tpu_workflows")

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"


class _Store:
    def __init__(self, storage: str, workflow_id: str):
        self.dir = os.path.join(storage, workflow_id)

    def exists(self) -> bool:
        return os.path.isdir(self.dir)

    def _ensure(self):
        # lazy: read-only queries (get_status of a typo id, list_all over a
        # storage root with stray files) must not mutate storage
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    def _meta_path(self):
        return os.path.join(self.dir, "meta.json")

    def write_meta(self, **kwargs):
        self._ensure()
        meta = self.read_meta()
        meta.update(kwargs)
        meta["updated_at"] = time.time()
        with open(self._meta_path(), "w") as f:
            json.dump(meta, f)

    def read_meta(self) -> dict:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}

    def step_path(self, step_id: str) -> str:
        # continuation steps namespace under their parent ("003_x/001_y"):
        # sub-workflow checkpoints live in a per-step subtree
        return os.path.join(self.dir, "steps", step_id + ".pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self.step_path(step_id))

    def save_step(self, step_id: str, value: Any):
        self._ensure()
        path = self.step_path(step_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, path)  # atomic commit

    def load_step(self, step_id: str) -> Any:
        with open(self.step_path(step_id), "rb") as f:
            return pickle.load(f)

    def save_graph(self, dag: DAGNode, input_args: tuple):
        import cloudpickle  # graphs close over user functions

        self._ensure()
        with open(os.path.join(self.dir, "graph.pkl"), "wb") as f:
            cloudpickle.dump((dag, input_args), f)

    def load_graph(self):
        with open(os.path.join(self.dir, "graph.pkl"), "rb") as f:
            return pickle.load(f)

    def append_event(self, event: dict) -> None:
        """Durable event log (reference: workflow event system /
        workflow_executor status callbacks) — one JSON line per event.
        Callers pass events already carrying their ``time``."""
        self._ensure()
        with open(os.path.join(self.dir, "events.jsonl"), "a") as f:
            f.write(json.dumps(event) + "\n")

    def read_events(self) -> list[dict]:
        out = []
        try:
            with open(os.path.join(self.dir, "events.jsonl")) as f:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        break  # torn tail (crash mid-append): intact prefix wins
        except OSError:
            pass
        return out


class _PrefixStore:
    """Store view for a continuation: step ids namespace under the parent
    step, events share the root workflow's log."""

    def __init__(self, store, prefix: str):
        self._store = store
        self._prefix = prefix

    @property
    def dir(self):
        return self._store.dir

    def has_step(self, step_id: str) -> bool:
        return self._store.has_step(self._prefix + step_id)

    def save_step(self, step_id: str, value: Any):
        self._store.save_step(self._prefix + step_id, value)

    def load_step(self, step_id: str) -> Any:
        return self._store.load_step(self._prefix + step_id)

    def append_event(self, event: dict) -> None:
        self._store.append_event(event)


class Continuation:
    """A step's return value saying "the workflow continues with THIS DAG"
    (reference: ``workflow.continuation`` — dynamic/recursive workflows).
    The sub-DAG executes durably with its steps namespaced under the
    returning step; the step's checkpoint is the sub-workflow's result, so
    a resume never re-enters a finished continuation."""

    def __init__(self, dag: DAGNode, *input_args):
        if not isinstance(dag, DAGNode):
            raise TypeError("continuation(dag) takes a bound DAG node")
        self.dag = dag
        self.input_args = input_args


def continuation(dag: DAGNode, *input_args) -> Continuation:
    return Continuation(dag, *input_args)


class EventNode(DAGNode):
    """A workflow step that blocks until an external event arrives
    (reference: ``workflow.wait_for_event`` + the event-listener system).
    The event payload is the step's (checkpointed) value — a crash after
    the event committed never waits for it again."""

    def __init__(self, name: str, timeout_s: Optional[float] = None, poll_s: float = 0.2):
        super().__init__((), {})
        self.name = name
        self.timeout_s = timeout_s
        self.poll_s = poll_s


def wait_for_event(name: str, timeout_s: Optional[float] = None) -> EventNode:
    return EventNode(name, timeout_s)


def send_event(
    workflow_id: str, name: str, payload: Any = None, storage: Optional[str] = None
) -> None:
    """Deliver an external event to a (possibly not-yet-waiting) workflow.
    Durable: the payload commits to the workflow's storage, so delivery
    survives both driver and sender crashes."""
    store = _Store(storage or _DEFAULT_STORAGE, workflow_id)
    store._ensure()
    evdir = os.path.join(store.dir, "events_in")
    os.makedirs(evdir, exist_ok=True)
    tmp = os.path.join(evdir, name + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, os.path.join(evdir, name + ".pkl"))


@ray_tpu.remote(num_cpus=0)
def _await_event(store_dir: str, name: str, timeout_s: Optional[float], poll_s: float):
    """The event-wait step body: poll the durable mailbox (num_cpus=0 — a
    parked waiter must not hold a CPU slot away from real steps)."""
    path = os.path.join(store_dir, "events_in", name + ".pkl")
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while not os.path.exists(path):
        if deadline is not None and time.monotonic() >= deadline:
            raise TimeoutError(f"workflow event {name!r} not delivered in {timeout_s}s")
        time.sleep(poll_s)
    with open(path, "rb") as f:
        return pickle.load(f)


def _step_ids(dag: DAGNode) -> dict[int, str]:
    """Deterministic id per node: function name + topological index +
    structure hash — stable across process restarts for the same graph."""
    order: list[DAGNode] = []
    seen: set[int] = set()

    def walk(node):
        if not isinstance(node, DAGNode) or id(node) in seen:
            return
        seen.add(id(node))
        for v in list(node._bound_args) + list(node._bound_kwargs.values()):
            walk(v)
        order.append(node)

    walk(dag)
    ids: dict[int, str] = {}
    for idx, node in enumerate(order):
        name = type(node).__name__
        if isinstance(node, FunctionNode):
            # RemoteFunction wraps the user function as ._fn (and
            # update_wrapper copies __name__ onto the wrapper itself)
            name = getattr(
                getattr(node._fn, "_fn", node._fn), "__name__", None
            ) or getattr(node._fn, "__name__", "fn")
        ids[id(node)] = f"{idx:03d}_{name}_{hashlib.sha1(name.encode()).hexdigest()[:6]}"
    return ids


def _execute_durable(
    dag: DAGNode, input_args: tuple, store: _Store, on_event=None
) -> Any:
    """Durable, CONCURRENT DAG execution.

    Steps are submitted eagerly with ObjectRef arguments, so independent
    branches run in parallel across the cluster (reference:
    workflow_executor's in-flight task set) — the scheduler, not this
    loop, decides concurrency. The driver persists each step's result as
    it completes (ray_tpu.wait harvest): crash anywhere and resume()
    re-submits only steps without a checkpoint. Per-step retries are the
    underlying TASK's ``max_retries`` (set via ``.options`` on the remote
    function when binding the DAG). Events go to the durable per-workflow
    log and to ``on_event`` as they happen."""
    ids = _step_ids(dag)
    memo: dict = {}
    inputs = list(input_args)
    for node in dag._collect_inputs():
        memo[id(node)] = inputs.pop(0) if inputs else None

    # steps some OTHER node consumes: continuations are tail-position only
    # (reference semantics) — a mid-DAG consumer is submitted eagerly with
    # the producer's ref and would receive the raw Continuation object, so
    # that shape must fail loudly, not compute garbage
    steps_with_dependents: set[str] = set()
    _seen_dep: set[int] = set()

    def _mark_deps(node):
        if not isinstance(node, DAGNode) or id(node) in _seen_dep:
            return
        _seen_dep.add(id(node))
        for v in list(node._bound_args) + list(node._bound_kwargs.values()):
            if isinstance(v, DAGNode):
                steps_with_dependents.add(ids[id(v)])
                _mark_deps(v)

    _mark_deps(dag)

    def emit(event_type: str, step_id: str) -> None:
        event = {"type": event_type, "step_id": step_id, "time": time.time()}
        store.append_event(event)
        if on_event is not None:
            try:
                on_event(dict(event))
            except Exception:
                pass  # a broken listener must not kill the workflow

    pending: dict[Any, str] = {}  # ref -> step_id (awaiting checkpoint)
    resolved: dict[Any, Any] = {}  # ref -> final value (continuations differ
    # from the raw task result, so materialize must NOT re-get those refs)

    def _deref_lists(v):
        """A MultiOutputNode upstream produces a LIST of in-flight refs:
        nested refs would pickle by value with no dependency edge, so a
        consumer could run before its producers. Materialize list-shaped
        inputs here (only that branch blocks)."""
        from ray_tpu._private.runtime import ObjectRef

        if isinstance(v, list):
            return [
                ray_tpu.get(x) if isinstance(x, ObjectRef) else _deref_lists(x)
                for x in v
            ]
        return v

    def build(node: DAGNode):
        """Returns the node's value (checkpointed) or an ObjectRef
        (submitted, in flight) — WITHOUT blocking, so siblings overlap."""
        key = id(node)
        if key in memo:
            return memo[key]
        step_id = ids[key]
        if store.has_step(step_id):
            memo[key] = store.load_step(step_id)  # checkpointed — skip
            return memo[key]
        args = [_deref_lists(build(a)) if isinstance(a, DAGNode) else a for a in node._bound_args]
        kwargs = {
            k: (_deref_lists(build(v)) if isinstance(v, DAGNode) else v)
            for k, v in node._bound_kwargs.items()
        }
        if isinstance(node, MultiOutputNode):
            value = list(args)  # refs/values; materialized at harvest
        elif isinstance(node, EventNode):
            value = _await_event.remote(
                store.dir, node.name, node.timeout_s, node.poll_s
            )
            pending[value] = step_id
            emit("step_started", step_id)
        elif isinstance(node, FunctionNode):
            # submit, don't wait: ref args chain dependencies through the
            # scheduler; task max_retries = the step's retry budget
            value = node._fn.remote(*args, **kwargs)
            pending[value] = step_id
            emit("step_started", step_id)
        elif hasattr(node, "_cls"):  # ClassNode — uses the DURABLY computed
            # args, but actor handles themselves aren't durable: not
            # checkpointed (reference: virtual actors are a separate system)
            value = node._cls.remote(*args, **kwargs)
        else:
            raise TypeError(f"workflows cannot execute {type(node).__name__}")
        memo[key] = value
        return value

    def harvest(best_effort: bool = False) -> Optional[BaseException]:
        """Checkpoint step results AS THEY COMPLETE, whatever order the
        branches finish in; returns the first step failure (siblings are
        saved before it surfaces — resume then re-runs only the failure
        and its dependents)."""
        failure: Optional[BaseException] = None
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=None)
            for ref in ready:
                step_id = pending.pop(ref)
                try:
                    value = ray_tpu.get(ref)
                except Exception as e:  # STEP failure (KeyboardInterrupt etc.
                    # propagate immediately — they are driver-level, not steps)
                    emit("step_failed", step_id)
                    if failure is None:
                        failure = e
                    continue
                if isinstance(value, Continuation) and not best_effort:
                    if step_id in steps_with_dependents:
                        emit("step_failed", step_id)
                        if failure is None:
                            failure = TypeError(
                                f"step {step_id!r} returned a continuation but "
                                "has downstream consumers — continuations are "
                                "tail-position only (its consumers were "
                                "submitted eagerly and would receive the raw "
                                "Continuation object)"
                            )
                        continue
                    # dynamic workflow: the step's "result" is a sub-DAG;
                    # execute it durably, namespaced under this step — the
                    # checkpoint below is the continuation's FINAL value
                    emit("continuation_started", step_id)
                    try:
                        value = _execute_durable(
                            value.dag,
                            value.input_args,
                            _PrefixStore(store, step_id + "/"),
                            on_event=on_event,
                        )
                    except Exception as e:  # sub-workflow failed
                        emit("step_failed", step_id)
                        if failure is None:
                            failure = e
                        continue
                resolved[ref] = value
                try:
                    # a save failure is a DRIVER/storage problem, not a step
                    # failure: surface it now rather than re-running a step
                    # that already succeeded on the cluster
                    store.save_step(step_id, value)
                except Exception:
                    if not best_effort:
                        raise
                    continue  # cleanup path: keep draining the other refs
                emit("step_completed", step_id)
        return failure

    try:
        root = build(dag)
    except Exception:
        # a build-phase failure (e.g. materializing a failed MultiOutput
        # branch) must still checkpoint completed siblings before raising —
        # best-effort, so a secondary storage error can't mask the root cause
        try:
            harvest(best_effort=True)
        except Exception:
            pass
        raise
    failure = harvest()
    if failure is not None:
        raise failure

    def materialize(v):
        if isinstance(v, list):
            return [materialize(x) for x in v]
        from ray_tpu._private.runtime import ObjectRef

        if isinstance(v, ObjectRef):
            return resolved[v] if v in resolved else ray_tpu.get(v)
        return v

    return materialize(root)


def _execute_with_retries(
    dag, input_args, store, on_event, max_step_retries: int
) -> Any:
    """Step retries, resume-style (reference: workflow max_retries): a
    failed round re-drives the DAG — checkpointed steps load instantly, so
    each extra round re-runs ONLY the failed step and its dependents.
    (Task-level ``max_retries`` still covers worker-death retries
    underneath; this layer covers application exceptions.)"""
    attempts = 0
    while True:
        try:
            return _execute_durable(dag, input_args, store, on_event=on_event)
        except Exception:
            attempts += 1
            if attempts > max_step_retries:
                raise
            event = {"type": "retry_round", "round": attempts, "time": time.time()}
            store.append_event(event)
            if on_event is not None:
                try:
                    on_event(dict(event))
                except Exception as e:
                    from ray_tpu._private.log_util import warn_throttled

                    warn_throttled("workflow on_event callback", e)


def run(
    dag: DAGNode,
    *input_args,
    workflow_id: Optional[str] = None,
    storage: Optional[str] = None,
    on_event=None,
    max_step_retries: int = 0,
) -> Any:
    """Execute a DAG durably; returns the final result (reference:
    ``workflow.run``). Independent branches run CONCURRENTLY; ``on_event``
    receives {type, step_id, time} dicts live (also persisted — see
    ``get_events``). ``max_step_retries`` re-drives failed rounds
    (checkpointed steps are skipped) — opt-in, since retrying
    non-idempotent steps repeats their side effects."""
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    store = _Store(storage or _DEFAULT_STORAGE, workflow_id)
    store.save_graph(dag, input_args)
    store.write_meta(status=STATUS_RUNNING, workflow_id=workflow_id)
    try:
        out = _execute_with_retries(dag, input_args, store, on_event, max_step_retries)
    except BaseException:
        store.write_meta(status=STATUS_FAILED)
        raise
    store.write_meta(status=STATUS_SUCCESSFUL)
    store.save_step("__output__", out)
    return out


def resume(
    workflow_id: str,
    storage: Optional[str] = None,
    on_event=None,
    max_step_retries: int = 0,
) -> Any:
    """Re-drive an interrupted workflow; completed steps are loaded from
    storage, remaining steps execute (reference: ``workflow.resume``)."""
    store = _Store(storage or _DEFAULT_STORAGE, workflow_id)
    if store.has_step("__output__"):
        return store.load_step("__output__")
    dag, input_args = store.load_graph()
    store.write_meta(status=STATUS_RUNNING)
    try:
        out = _execute_with_retries(dag, input_args, store, on_event, max_step_retries)
    except BaseException:
        store.write_meta(status=STATUS_FAILED)
        raise
    store.write_meta(status=STATUS_SUCCESSFUL)
    store.save_step("__output__", out)
    return out


def get_status(workflow_id: str, storage: Optional[str] = None) -> Optional[str]:
    store = _Store(storage or _DEFAULT_STORAGE, workflow_id)
    if not store.exists():
        return None
    return store.read_meta().get("status")


def get_output(workflow_id: str, storage: Optional[str] = None) -> Any:
    store = _Store(storage or _DEFAULT_STORAGE, workflow_id)
    if not store.has_step("__output__"):
        raise ValueError(f"workflow {workflow_id!r} has no output (not finished?)")
    return store.load_step("__output__")


def get_events(workflow_id: str, storage: Optional[str] = None) -> list[dict]:
    """The workflow's durable event log: step_started / step_completed /
    step_failed lines with timestamps (reference: the workflow event
    system's observable execution feed)."""
    return _Store(storage or _DEFAULT_STORAGE, workflow_id).read_events()


def list_all(storage: Optional[str] = None) -> list[tuple[str, Optional[str]]]:
    root = storage or _DEFAULT_STORAGE
    out = []
    if os.path.isdir(root):
        for wid in sorted(os.listdir(root)):
            if os.path.isdir(os.path.join(root, wid)):  # skip stray files
                out.append((wid, get_status(wid, root)))
    return out
