"""Per-worker training session.

Reference: ``python/ray/train/_internal/session.py`` — the user's
``train_loop_per_worker`` runs on a side thread inside each train worker;
``report(metrics, checkpoint)`` (:394, public :654) hands results to the
driver, ``get_checkpoint`` (:741) exposes the restore point,
``get_dataset_shard`` (:1047) the per-worker data iterator.

The session queue is bounded at 1: ``report`` blocks until the driver has
consumed the previous result, keeping all workers in lockstep the way the
reference's backend executor does.
"""

from __future__ import annotations

import dataclasses
import inspect
import queue
import threading
from typing import Any, Callable, Optional

from ray_tpu.train._checkpoint import Checkpoint


@dataclasses.dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str = "train"
    trial_name: str = "trial"
    trial_id: str = "0"

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id


class _TrainSession:
    def __init__(
        self,
        train_fn: Callable,
        config: Optional[dict],
        context: TrainContext,
        checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[dict] = None,
    ):
        self.train_fn = train_fn
        self.config = config or {}
        self.context = context
        self.checkpoint = checkpoint
        self.dataset_shards = dataset_shards or {}
        self.out: "queue.Queue" = queue.Queue(maxsize=1)
        self.ack_event = threading.Event()
        self.thread: Optional[threading.Thread] = None
        self.finished = False

    def start(self):
        self.thread = threading.Thread(target=self._run, name="train-loop", daemon=True)
        self.thread.start()

    def _run(self):
        global _session
        _session = self
        try:
            sig = inspect.signature(self.train_fn)
            if len(sig.parameters) >= 1:
                ret = self.train_fn(self.config)
            else:
                ret = self.train_fn()
            self.out.put(("done", ret, None))
        except BaseException as e:  # noqa: BLE001 — crosses to the driver
            import traceback

            self.out.put(("error", e, traceback.format_exc()))
        finally:
            _session = None

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        """Blocks until the driver has consumed AND committed this result
        (ack roundtrip) — a crash after report() can never lose a reported
        checkpoint, matching the reference's synchronous checkpoint upload."""
        self.ack_event.clear()
        self.out.put(("result", metrics, checkpoint))
        self.ack_event.wait()

    def next(self, timeout: Optional[float] = None):
        """Called by the worker actor: next event or None on timeout."""
        try:
            return self.out.get(timeout=timeout)
        except queue.Empty:
            return None


_session: Optional[_TrainSession] = None


def _get_session(ok_if_missing: bool = False) -> Optional[_TrainSession]:
    if _session is None and not ok_if_missing:
        raise RuntimeError(
            "No train session active. ray_tpu.train.report()/get_context() "
            "must be called inside train_loop_per_worker."
        )
    return _session


def report(metrics: dict, checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (+ optional checkpoint) to the trainer
    (reference ``session.py:654``)."""
    _get_session().report(dict(metrics), checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """Latest committed checkpoint to resume from (reference
    ``session.py:741``)."""
    s = _get_session(ok_if_missing=True)
    return s.checkpoint if s else None


def get_context() -> TrainContext:
    s = _get_session(ok_if_missing=True)
    if s is None:
        return TrainContext(1, 0, 0, 1, 0)
    return s.context


def get_dataset_shard(dataset_name: str = "train"):
    """Per-worker shard of a dataset passed to the trainer (reference
    ``session.py:1047`` backed by Ray Data streaming_split)."""
    s = _get_session()
    shard = s.dataset_shards.get(dataset_name)
    if shard is None:
        raise KeyError(
            f"No dataset shard named {dataset_name!r}; pass datasets={{...}} to the trainer"
        )
    return shard
