"""Cloud-capable persistence on ``pyarrow.fs``.

Reference: ``python/ray/train/_internal/storage.py`` (StorageContext) — the
reference persists checkpoints and experiment state to S3/GS/NFS through one
``pyarrow.fs.FileSystem`` handle resolved from the ``storage_path`` URI, and
every other layer (Checkpoint, CheckpointManager, Tune experiment snapshots)
rides that handle instead of touching ``os``/``shutil`` directly. Same design
here: ``s3://…``, ``gs://…``, ``file:///…`` and bare local paths all resolve
through :func:`get_fs_and_path`; tests inject a custom filesystem (e.g. a
``SubTreeFileSystem`` over a tmpdir) via ``storage_filesystem`` exactly like
the reference's ``storage_filesystem`` argument.

TPU angle: checkpoints on a pod must outlive any single host (a lost host
kills the mesh and the job restarts from storage — SURVEY §7 "rely on
checkpoint-restart elasticity"), so the persistence tier has to be DCN/cloud
storage, not a host-local directory.
"""

from __future__ import annotations

import json
import os
import posixpath
from typing import Optional, Tuple


def is_uri(path: str) -> bool:
    return "://" in str(path)


def get_fs_and_path(
    path: str, storage_filesystem=None
) -> Tuple["object", str]:
    """Resolve ``path`` to ``(pyarrow.fs.FileSystem, fs-internal path)``.

    With ``storage_filesystem`` given, ``path`` is taken as already
    fs-internal (reference: ``StorageContext.__init__`` custom-fs branch).
    """
    from pyarrow import fs as pafs

    if storage_filesystem is not None:
        return storage_filesystem, str(path).rstrip("/")
    if is_uri(path):
        fs, fs_path = pafs.FileSystem.from_uri(str(path))
        return fs, fs_path
    return pafs.LocalFileSystem(), os.path.abspath(os.path.expanduser(path))


def fs_join(*parts: str) -> str:
    return posixpath.join(*[p for p in parts if p != ""])


def exists(fs, fs_path: str) -> bool:
    from pyarrow import fs as pafs

    info = fs.get_file_info(fs_path)
    return info.type != pafs.FileType.NotFound


def upload_dir(fs, fs_path: str, local_dir: str) -> None:
    """Recursively copy a local directory into ``fs_path`` on ``fs``."""
    fs.create_dir(fs_path, recursive=True)
    for root, _dirs, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        dest_root = fs_path if rel == "." else fs_join(fs_path, rel.replace(os.sep, "/"))
        if rel != ".":
            fs.create_dir(dest_root, recursive=True)
        for name in files:
            with open(os.path.join(root, name), "rb") as src, fs.open_output_stream(
                fs_join(dest_root, name)
            ) as dst:
                while True:
                    chunk = src.read(4 << 20)
                    if not chunk:
                        break
                    dst.write(chunk)


def download_dir(fs, fs_path: str, local_dir: str) -> None:
    """Recursively copy ``fs_path`` on ``fs`` into a local directory."""
    from pyarrow import fs as pafs

    os.makedirs(local_dir, exist_ok=True)
    selector = pafs.FileSelector(fs_path, recursive=True)
    for info in fs.get_file_info(selector):
        rel = posixpath.relpath(info.path, fs_path)
        dest = os.path.join(local_dir, *rel.split("/"))
        if info.type == pafs.FileType.Directory:
            os.makedirs(dest, exist_ok=True)
            continue
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with fs.open_input_stream(info.path) as src, open(dest, "wb") as dst:
            while True:
                chunk = src.read(4 << 20)
                if not chunk:
                    break
                dst.write(chunk)


def delete_dir(fs, fs_path: str) -> None:
    try:
        fs.delete_dir(fs_path)
    except FileNotFoundError:
        pass
    except OSError as e:
        # a silently-failed prune would let keep-N grow unboundedly on cloud
        # storage with zero operator signal — log, don't raise (the commit
        # that triggered the prune must still succeed)
        print(f"[ray_tpu.train] storage delete of {fs_path!r} failed: {e!r}")


def write_json(fs, fs_path: str, obj) -> None:
    parent = posixpath.dirname(fs_path)
    if parent:
        fs.create_dir(parent, recursive=True)
    with fs.open_output_stream(fs_path) as f:
        f.write(json.dumps(obj, indent=1).encode())


def read_json(fs, fs_path: str):
    with fs.open_input_stream(fs_path) as f:
        return json.loads(f.read().decode())


class StorageContext:
    """One experiment's persistence root: ``<storage_path>/<experiment>/
    [<trial>]`` on a pyarrow filesystem (reference:
    ``train/_internal/storage.py`` StorageContext fields of the same shape).

    ``uri_for(rel)`` returns a string that round-trips through
    :func:`get_fs_and_path` — the original URI form when one was given, else
    a plain local path.
    """

    def __init__(
        self,
        storage_path: str,
        experiment_name: str,
        trial_name: Optional[str] = None,
        storage_filesystem=None,
    ):
        self.storage_path = str(storage_path)
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.custom_fs = storage_filesystem is not None
        self.fs, self.base_path = get_fs_and_path(storage_path, storage_filesystem)
        self.experiment_fs_path = fs_join(self.base_path, experiment_name)
        self.trial_fs_path = (
            fs_join(self.experiment_fs_path, trial_name) if trial_name else None
        )

    def for_trial(self, trial_name: str) -> "StorageContext":
        ctx = StorageContext.__new__(StorageContext)
        ctx.storage_path = self.storage_path
        ctx.experiment_name = self.experiment_name
        ctx.trial_name = trial_name
        ctx.custom_fs = self.custom_fs
        ctx.fs = self.fs
        ctx.base_path = self.base_path
        ctx.experiment_fs_path = self.experiment_fs_path
        ctx.trial_fs_path = fs_join(self.experiment_fs_path, trial_name)
        return ctx

    # -- naming ------------------------------------------------------------
    def _rel_to_fs_path(self, rel: str) -> str:
        root = self.trial_fs_path or self.experiment_fs_path
        return fs_join(root, rel) if rel else root

    def uri_for(self, rel: str = "") -> str:
        """External name for ``rel`` under this context. URI-form storage
        paths keep their scheme so ``Checkpoint.from_uri`` round-trips."""
        if self.custom_fs:
            # no scheme to reconstruct — callers must hold the fs handle
            return self._rel_to_fs_path(rel)
        if is_uri(self.storage_path):
            scheme, rest = self.storage_path.split("://", 1)
            tail = [self.experiment_name]
            if self.trial_name:
                tail.append(self.trial_name)
            if rel:
                tail.append(rel)
            return f"{scheme}://{fs_join(rest.rstrip('/'), *tail)}"
        return self._rel_to_fs_path(rel)

    # -- operations --------------------------------------------------------
    def persist_dir(self, local_dir: str, rel: str) -> str:
        """Upload a local directory to ``rel`` under the trial root; returns
        its external name (see ``uri_for``)."""
        upload_dir(self.fs, self._rel_to_fs_path(rel), local_dir)
        return self.uri_for(rel)

    def restore_dir(self, rel: str, local_dir: str) -> str:
        download_dir(self.fs, self._rel_to_fs_path(rel), local_dir)
        return local_dir

    def delete(self, rel: str) -> None:
        delete_dir(self.fs, self._rel_to_fs_path(rel))

    def exists(self, rel: str = "") -> bool:
        return exists(self.fs, self._rel_to_fs_path(rel))

    def write_json(self, rel: str, obj) -> None:
        write_json(self.fs, self._rel_to_fs_path(rel), obj)

    def read_json(self, rel: str):
        return read_json(self.fs, self._rel_to_fs_path(rel))

    def list_dir(self, rel: str = "") -> list[str]:
        from pyarrow import fs as pafs

        root = self._rel_to_fs_path(rel)
        if not exists(self.fs, root):
            return []
        sel = pafs.FileSelector(root, recursive=False)
        return sorted(posixpath.basename(i.path) for i in self.fs.get_file_info(sel))
