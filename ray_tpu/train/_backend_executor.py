"""BackendExecutor: drives a worker group through one training run.

Reference: ``python/ray/train/_internal/backend_executor.py`` — ``start``
:124 (spawn group, backend.on_start), ``start_training`` :438,
``get_with_failure_handling`` :640. The JAX backend's ``on_start`` is the
TPU counterpart of ``_setup_torch_process_group`` (``train/torch/config.py:
47-91``): instead of ``dist.init_process_group(nccl)``, hosts learn the
rank-0 coordinator address so ``jax.distributed.initialize`` can join them
into one global device mesh; collectives then compile onto ICI/DCN.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import ray_tpu
from ray_tpu.train._config import JaxConfig, ScalingConfig
from ray_tpu.train._session import TrainContext
from ray_tpu.train._worker_group import WorkerGroup


class TrainingWorkerError(RuntimeError):
    def __init__(self, rank: int, cause: BaseException, tb: Optional[str]):
        super().__init__(f"worker rank={rank} failed: {cause}")
        self.rank = rank
        self.cause = cause
        self.tb = tb


class JaxBackend:
    """Mesh bring-up across hosts."""

    def __init__(self, config: Optional[JaxConfig] = None):
        self.config = config or JaxConfig()

    def on_start(self, wg: WorkerGroup) -> None:
        # rank-0 host is the jax.distributed coordinator (the reference
        # broadcasts rank-0's addr for init_process_group the same way)
        rank0 = wg.ranks.index(0)
        coord = f"{wg.infos[rank0]['ip']}:{self.config.coordinator_port}"
        envs = []
        for i in range(wg.num_workers):
            env = {
                "RAY_TRAIN_COORDINATOR_ADDRESS": coord,
                "RAY_TRAIN_NUM_PROCESSES": str(wg.num_workers),
                "RAY_TRAIN_PROCESS_ID": str(wg.ranks[i]),
            }
            envs.append(env)
        wg.set_env(envs)
        if self.config.init_distributed and wg.num_workers > 1:
            wg.execute(_jax_distributed_init)

    def on_shutdown(self, wg: WorkerGroup) -> None:
        if self.config.init_distributed and wg.num_workers > 1:
            try:
                wg.execute(_jax_distributed_shutdown)
            except Exception:
                pass


def _jax_distributed_init():
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ["RAY_TRAIN_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["RAY_TRAIN_NUM_PROCESSES"]),
        process_id=int(os.environ["RAY_TRAIN_PROCESS_ID"]),
    )


def _jax_distributed_shutdown():
    import jax

    jax.distributed.shutdown()


class BackendExecutor:
    def __init__(
        self,
        scaling: ScalingConfig,
        backend: Optional[JaxBackend] = None,
        experiment_name: str = "train",
        trial_name: str = "trial",
    ):
        self.scaling = scaling
        self.backend = backend or JaxBackend()
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.wg: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.wg = WorkerGroup(
            self.scaling.num_workers,
            self.scaling.worker_resources(),
            self.scaling.placement_strategy,
        )
        self.backend.on_start(self.wg)

    def start_training(
        self,
        train_fn: Callable,
        config: Optional[dict],
        checkpoint,
        dataset_splitter: Optional[Callable[[int, int], dict]] = None,
    ) -> None:
        assert self.wg is not None
        calls = []
        for i, w in enumerate(self.wg.workers):
            ctx: TrainContext = self.wg.context_for(i, self.experiment_name, self.trial_name)
            shards = dataset_splitter(ctx.world_rank, ctx.world_size) if dataset_splitter else None
            calls.append(w.start_training.remote(train_fn, config, ctx, checkpoint, shards))
        try:
            ray_tpu.get(calls)
        except Exception as e:
            # a worker can die before even acking start (instant user crash)
            raise TrainingWorkerError(-1, e, None) from e

    def next_results(self, done_mask=None, timeout_per_wait: float = 10.0, deadline_s: float = 3600.0):
        """One event from every not-yet-done worker (lockstep; reference
        ``get_with_failure_handling``). Long-lived ``next_result`` futures
        are consumed in completion order via ``ray_tpu.wait`` — one in-flight
        call per worker instead of a 1 Hz poll per worker (the reference uses
        futures the same way; a polling loop is a control-plane storm at
        64-host scale). Returns list of events (None for workers already
        done); raises TrainingWorkerError on worker failure, TimeoutError
        past ``deadline_s`` (guards against unequal report counts across
        workers deadlocking the loop)."""
        import time as _time

        assert self.wg is not None
        events: list = [None] * len(self.wg.workers)
        pending = {
            i for i in range(len(self.wg.workers)) if not (done_mask and done_mask[i])
        }
        futures: dict = {}  # future -> worker index
        deadline = _time.monotonic() + deadline_s
        while pending:
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"train workers {sorted(pending)} produced no result for "
                    f"{deadline_s}s — check that every worker calls "
                    f"ray_tpu.train.report() the same number of times"
                )
            inflight = set(futures.values())
            for i in sorted(pending - inflight):
                futures[self.wg.workers[i].next_result.remote(timeout_per_wait)] = i
            ready, _ = ray_tpu.wait(list(futures), num_returns=1, timeout=5.0)
            if not ready:
                continue
            fut = ready[0]
            i = futures.pop(fut)
            try:
                ev = ray_tpu.get(fut)
            except Exception as e:  # actor died
                raise TrainingWorkerError(self.wg.ranks[i], e, None) from e
            if ev is None:
                continue  # worker had nothing within timeout_per_wait; re-arm
            if ev[0] == "error":
                raise TrainingWorkerError(self.wg.ranks[i], ev[1], ev[2])
            events[i] = ev
            pending.discard(i)
        return events

    def shutdown(self):
        if self.wg is not None:
            try:
                self.backend.on_shutdown(self.wg)
            finally:
                self.wg.shutdown()
                self.wg = None
