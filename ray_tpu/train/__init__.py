"""ray_tpu.train — distributed training on TPU meshes.

Public surface mirrors the reference's ``ray.train`` (SURVEY §2.3): configs,
Checkpoint, session functions (report/get_context/get_checkpoint/
get_dataset_shard), DataParallelTrainer/JaxTrainer, Result.
"""

from ray_tpu.train._checkpoint import Checkpoint, load_pytree, save_pytree  # noqa: F401
from ray_tpu.train._config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._session import (  # noqa: F401
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.trainer import (  # noqa: F401
    DataParallelTrainer,
    JaxTrainer,
    Result,
)
from ray_tpu.train._backend_executor import JaxBackend  # noqa: F401
