"""Checkpoint persistence + keep-N bookkeeping.

Reference: ``python/ray/train/_internal/storage.py`` (StorageContext) +
checkpoint manager semantics of ``CheckpointConfig`` (``air/config.py:427``).
Workers report checkpoints as local dirs; the manager commits them under
``<storage>/<experiment>/<trial>/checkpoint_NNNNN`` and prunes by score/age.
With a :class:`~ray_tpu.train._storage.StorageContext` the commit target is
the (possibly cloud) filesystem — local reported dirs are uploaded and the
returned handles are remote, so a dead head loses nothing.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import CheckpointConfig
from ray_tpu.train._storage import StorageContext


class CheckpointManager:
    def __init__(
        self,
        trial_dir: str,
        config: Optional[CheckpointConfig] = None,
        storage: Optional[StorageContext] = None,
    ):
        self.trial_dir = trial_dir
        self.config = config or CheckpointConfig()
        self.storage = storage
        # (score, idx, name) — name is a local path without storage, else the
        # checkpoint's rel name under the trial's storage root
        self.committed: list[tuple[Optional[float], int, str]] = []
        self.index = 0
        if storage is None:
            os.makedirs(trial_dir, exist_ok=True)

    def _checkpoint_for(self, name: str) -> Checkpoint:
        if self.storage is None:
            return Checkpoint(name)
        if self.storage.custom_fs:
            return Checkpoint(
                self.storage._rel_to_fs_path(name), filesystem=self.storage.fs
            )
        return Checkpoint(self.storage.uri_for(name))

    def commit(self, reported: Checkpoint, metrics: dict) -> Checkpoint:
        name = f"checkpoint_{self.index:06d}"
        idx = self.index
        self.index += 1
        if self.storage is not None:
            # fresh-destination invariant (matches the local rmtree branch):
            # a re-run reusing the experiment name must not merge new files
            # into a previous run's checkpoint_NNNNNN
            self.storage.delete(name)
            with reported.as_directory() as local:
                self.storage.persist_dir(local, name)
            ckpt = self._checkpoint_for(name)
        else:
            dest = os.path.join(self.trial_dir, name)
            if os.path.abspath(reported.path) != dest:
                if os.path.exists(dest):
                    shutil.rmtree(dest)
                shutil.copytree(reported.path, dest)
            name = dest
            ckpt = Checkpoint(dest)
        ckpt.update_metadata({"metrics": _json_safe(metrics), "index": idx})
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr is not None and attr in metrics:
            try:
                score = float(metrics[attr])
            except (TypeError, ValueError):
                score = None
        self.committed.append((score, idx, name))
        self._prune()
        return ckpt

    def _delete(self, name: str) -> None:
        if self.storage is not None:
            self.storage.delete(name)
        elif os.path.exists(name):
            shutil.rmtree(name, ignore_errors=True)

    def _prune(self):
        keep = self.config.num_to_keep
        if keep is None or len(self.committed) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            victims = self.committed[: len(self.committed) - keep]  # oldest first
            self.committed = self.committed[len(self.committed) - keep:]
        else:
            # rank best-first; unscored checkpoints always rank weakest
            sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
            ranked = sorted(
                self.committed,
                key=lambda t: (t[0] is not None, sign * t[0] if t[0] is not None else 0.0),
                reverse=True,
            )
            self.committed = ranked[:keep]
            victims = ranked[keep:]
        keep_names = {p for _, _, p in self.committed}
        for _, _, name in victims:
            if name not in keep_names:
                self._delete(name)

    def latest(self) -> Optional[Checkpoint]:
        if not self.committed:
            return None
        _, _, name = max(self.committed, key=lambda t: t[1])
        return self._checkpoint_for(name)

    def best(self) -> Optional[Checkpoint]:
        scored = [t for t in self.committed if t[0] is not None]
        if not scored:
            return self.latest()
        pick = max if self.config.checkpoint_score_order == "max" else min
        return self._checkpoint_for(pick(scored, key=lambda t: t[0])[2])


def json_safe(obj):
    """Recursively replace non-JSON-serializable values with their repr."""
    import json

    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): json_safe(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [json_safe(v) for v in obj]
        return repr(obj)


_json_safe = json_safe  # internal alias
