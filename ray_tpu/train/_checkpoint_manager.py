"""Checkpoint persistence + keep-N bookkeeping.

Reference: ``python/ray/train/_internal/storage.py`` (StorageContext) +
checkpoint manager semantics of ``CheckpointConfig`` (``air/config.py:427``).
Workers report checkpoints as local dirs; the manager commits them under
``<storage>/<experiment>/<trial>/checkpoint_NNNNN`` and prunes by score/age.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._config import CheckpointConfig


class CheckpointManager:
    def __init__(self, trial_dir: str, config: Optional[CheckpointConfig] = None):
        self.trial_dir = trial_dir
        self.config = config or CheckpointConfig()
        self.committed: list[tuple[Optional[float], int, str]] = []  # (score, idx, path)
        self.index = 0
        os.makedirs(trial_dir, exist_ok=True)

    def commit(self, reported: Checkpoint, metrics: dict) -> Checkpoint:
        dest = os.path.join(self.trial_dir, f"checkpoint_{self.index:06d}")
        self.index += 1
        if os.path.abspath(reported.path) != dest:
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(reported.path, dest)
        ckpt = Checkpoint(dest)
        ckpt.update_metadata({"metrics": _json_safe(metrics), "index": self.index - 1})
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr is not None and attr in metrics:
            try:
                score = float(metrics[attr])
            except (TypeError, ValueError):
                score = None
        self.committed.append((score, self.index - 1, dest))
        self._prune()
        return ckpt

    def _prune(self):
        keep = self.config.num_to_keep
        if keep is None or len(self.committed) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            victims = self.committed[: len(self.committed) - keep]  # oldest first
            self.committed = self.committed[len(self.committed) - keep:]
        else:
            # rank best-first; unscored checkpoints always rank weakest
            sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
            ranked = sorted(
                self.committed,
                key=lambda t: (t[0] is not None, sign * t[0] if t[0] is not None else 0.0),
                reverse=True,
            )
            self.committed = ranked[:keep]
            victims = ranked[keep:]
        keep_paths = {p for _, _, p in self.committed}
        for _, _, path in victims:
            if path not in keep_paths and os.path.exists(path):
                shutil.rmtree(path, ignore_errors=True)

    def latest(self) -> Optional[Checkpoint]:
        if not self.committed:
            return None
        _, _, path = max(self.committed, key=lambda t: t[1])
        return Checkpoint(path)

    def best(self) -> Optional[Checkpoint]:
        scored = [t for t in self.committed if t[0] is not None]
        if not scored:
            return self.latest()
        pick = max if self.config.checkpoint_score_order == "max" else min
        return Checkpoint(pick(scored, key=lambda t: t[0])[2])


def json_safe(obj):
    """Recursively replace non-JSON-serializable values with their repr."""
    import json

    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): json_safe(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [json_safe(v) for v in obj]
        return repr(obj)


_json_safe = json_safe  # internal alias
