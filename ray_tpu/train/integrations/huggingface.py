"""HuggingFace transformers interop.

The reference integrates HF via torch Trainer callbacks
(``python/ray/train/huggingface/transformers/``). The TPU-native equivalent
is weight-level: convert a transformers GPT-2-family checkpoint into the
stacked-layer pytree that ``ray_tpu.models.gpt`` trains with pjit, so HF
models fine-tune on the JAX/XLA stack directly (no torch in the hot path).

The stacked layout (layer dim in front, consumed by ``lax.scan``) is the only
structural difference from the per-layer HF state dict; orientation of every
kernel matches (HF Conv1D already stores (in, out)).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_tpu.models.gpt import GPTConfig


def gpt_config_from_hf(hf_config: Any, **overrides) -> GPTConfig:
    """Build a ``GPTConfig`` from a ``transformers.GPT2Config``."""
    fields = dict(
        vocab_size=int(hf_config.vocab_size),
        seq_len=int(hf_config.n_positions),
        d_model=int(hf_config.n_embd),
        n_layers=int(hf_config.n_layer),
        n_heads=int(hf_config.n_head),
    )
    fields.update(overrides)
    return GPTConfig(**fields)


def _np(t) -> np.ndarray:
    """torch tensor / array-like -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def load_hf_gpt2(
    model_or_state_dict: Any,
    cfg: Optional[GPTConfig] = None,
    pad_vocab_to_multiple: int = 1,
) -> tuple[GPTConfig, dict]:
    """Convert a ``transformers`` GPT-2 model (or its state dict) into
    ``(GPTConfig, params)`` for ``ray_tpu.models.gpt``.

    ``pad_vocab_to_multiple=128`` pads the embedding/vocab dimension with
    zero rows for MXU-friendly shapes (padded ids are never produced by a
    tokenizer, so logits for them are inert).

    Works fully offline: pass ``GPT2LMHeadModel(GPT2Config(...))`` built
    locally, or any mapping of GPT-2 state-dict names to arrays.
    """
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        if cfg is None and hasattr(model_or_state_dict, "config"):
            cfg = gpt_config_from_hf(model_or_state_dict.config)
    else:
        sd = dict(model_or_state_dict)
    # accept both bare GPT2Model ("h.0...") and LMHead ("transformer.h.0...")
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    def get(name):
        return _np(sd[prefix + name])

    wte = get("wte.weight")
    wpe = get("wpe.weight")
    vocab, d = wte.shape
    if cfg is None:
        n_layers = 1 + max(
            int(k.split(".")[1 if not prefix else 2])
            for k in sd
            if ".h." in ("." + k) or k.startswith("h.")
        )
        raise ValueError(
            "pass cfg= or a model with .config (cannot infer n_heads from a "
            f"state dict; saw {n_layers} layers)"
        )
    if pad_vocab_to_multiple > 1:
        target = -(-vocab // pad_vocab_to_multiple) * pad_vocab_to_multiple
        if target != vocab:
            wte = np.concatenate([wte, np.zeros((target - vocab, d), np.float32)])
            import dataclasses

            cfg = dataclasses.replace(cfg, vocab_size=target)
    L = cfg.n_layers

    def stack(name):
        return np.stack([get(f"h.{i}.{name}") for i in range(L)])

    blocks = {
        "ln1": {"scale": stack("ln_1.weight"), "bias": stack("ln_1.bias")},
        "attn_qkv": {"kernel": stack("attn.c_attn.weight"), "bias": stack("attn.c_attn.bias")},
        "attn_out": {"kernel": stack("attn.c_proj.weight"), "bias": stack("attn.c_proj.bias")},
        "ln2": {"scale": stack("ln_2.weight"), "bias": stack("ln_2.bias")},
        "mlp_in": {"kernel": stack("mlp.c_fc.weight"), "bias": stack("mlp.c_fc.bias")},
        "mlp_out": {"kernel": stack("mlp.c_proj.weight"), "bias": stack("mlp.c_proj.bias")},
    }
    params = {
        "embed": {"tokens": wte, "pos": wpe},
        "blocks": blocks,
        "ln_f": {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")},
        # HF ties lm_head to wte (vocab, d); our head is (d, vocab)
        "lm_head": {"kernel": np.ascontiguousarray(wte.T)},
    }
    import jax
    import jax.numpy as jnp

    params = jax.tree.map(jnp.asarray, params)
    return cfg, params


# ---------------------------------------------------------------------------
# GPT-J (the reference's north-star model: release/air_examples/
# gptj_deepspeed_finetuning). HF GPTJForCausalLM -> models.gptj pytree.
# ---------------------------------------------------------------------------


def gptj_config_from_hf(hf_config: Any, **overrides):
    """Build a ``GPTJConfig`` from a ``transformers.GPTJConfig``."""
    from ray_tpu.models.gptj import GPTJConfig

    fields = dict(
        vocab_size=int(hf_config.vocab_size),
        seq_len=int(hf_config.n_positions),
        d_model=int(hf_config.n_embd),
        n_layers=int(hf_config.n_layer),
        n_heads=int(hf_config.n_head),
        # HF's fallback for rotary_dim=None is rotary over the FULL head —
        # the per-head dim, never n_embd (which would crash _apply_rotary)
        rotary_dim=int(
            getattr(hf_config, "rotary_dim", None)
            or hf_config.n_embd // hf_config.n_head
        ),
    )
    fields.update(overrides)
    return GPTJConfig(**fields)


def load_hf_gptj(
    model_or_state_dict: Any,
    cfg=None,
    pad_vocab_to_multiple: int = 1,
):
    """Convert a ``transformers`` GPT-J model (or state dict) into
    ``(GPTJConfig, params)`` for ``ray_tpu.models.gptj``.

    Orientation: HF GPT-J projections are ``nn.Linear`` storing (out, in) —
    every kernel transposes to the (in, out) matmul layout here (GPT-2's
    Conv1D did not need this). No q/k/v/out biases (GPT-J has none); the
    untied lm_head keeps its bias. ``pad_vocab_to_multiple=128`` zero-pads
    vocab rows for MXU lane alignment (50400 -> 50432); padded logits get a
    -1e9 head bias so greedy decode can never emit a padded id.
    """
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        if cfg is None and hasattr(model_or_state_dict, "config"):
            cfg = gptj_config_from_hf(model_or_state_dict.config)
    else:
        sd = dict(model_or_state_dict)
    if cfg is None:
        raise ValueError("pass cfg= or a model with .config")
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    def get(name):
        return _np(sd[prefix + name])

    wte = get("wte.weight")
    vocab, d = wte.shape
    lm_w = _np(sd["lm_head.weight"]).T          # (vocab, d) -> (d, vocab)
    lm_b = _np(sd["lm_head.bias"]) if "lm_head.bias" in sd else np.zeros(
        (vocab,), np.float32
    )
    if pad_vocab_to_multiple > 1:
        target = -(-vocab // pad_vocab_to_multiple) * pad_vocab_to_multiple
        if target != vocab:
            import dataclasses

            pad = target - vocab
            wte = np.concatenate([wte, np.zeros((pad, d), np.float32)])
            lm_w = np.concatenate([lm_w, np.zeros((d, pad), np.float32)], axis=1)
            lm_b = np.concatenate([lm_b, np.full((pad,), -1e9, np.float32)])
            cfg = dataclasses.replace(cfg, vocab_size=target)
    L = cfg.n_layers

    def stack(name, transpose=False):
        mats = [_np(sd[f"{prefix}h.{i}.{name}"]) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return np.stack(mats)

    blocks = {
        "ln1": {"scale": stack("ln_1.weight"), "bias": stack("ln_1.bias")},
        "q": {"kernel": stack("attn.q_proj.weight", transpose=True)},
        "k": {"kernel": stack("attn.k_proj.weight", transpose=True)},
        "v": {"kernel": stack("attn.v_proj.weight", transpose=True)},
        "attn_out": {"kernel": stack("attn.out_proj.weight", transpose=True)},
        "mlp_in": {
            "kernel": stack("mlp.fc_in.weight", transpose=True),
            "bias": stack("mlp.fc_in.bias"),
        },
        "mlp_out": {
            "kernel": stack("mlp.fc_out.weight", transpose=True),
            "bias": stack("mlp.fc_out.bias"),
        },
    }
    params = {
        "embed": {"tokens": wte},
        "blocks": blocks,
        "ln_f": {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")},
        "lm_head": {"kernel": lm_w, "bias": lm_b},
    }
    import jax
    import jax.numpy as jnp

    params = jax.tree.map(jnp.asarray, params)
    return cfg, params
