"""HuggingFace transformers interop.

The reference integrates HF via torch Trainer callbacks
(``python/ray/train/huggingface/transformers/``). The TPU-native equivalent
is weight-level: convert a transformers GPT-2-family checkpoint into the
stacked-layer pytree that ``ray_tpu.models.gpt`` trains with pjit, so HF
models fine-tune on the JAX/XLA stack directly (no torch in the hot path).

The stacked layout (layer dim in front, consumed by ``lax.scan``) is the only
structural difference from the per-layer HF state dict; orientation of every
kernel matches (HF Conv1D already stores (in, out)).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_tpu.models.gpt import GPTConfig


def gpt_config_from_hf(hf_config: Any, **overrides) -> GPTConfig:
    """Build a ``GPTConfig`` from a ``transformers.GPT2Config``."""
    fields = dict(
        vocab_size=int(hf_config.vocab_size),
        seq_len=int(hf_config.n_positions),
        d_model=int(hf_config.n_embd),
        n_layers=int(hf_config.n_layer),
        n_heads=int(hf_config.n_head),
    )
    fields.update(overrides)
    return GPTConfig(**fields)


def _np(t) -> np.ndarray:
    """torch tensor / array-like -> float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def load_hf_gpt2(
    model_or_state_dict: Any,
    cfg: Optional[GPTConfig] = None,
    pad_vocab_to_multiple: int = 1,
) -> tuple[GPTConfig, dict]:
    """Convert a ``transformers`` GPT-2 model (or its state dict) into
    ``(GPTConfig, params)`` for ``ray_tpu.models.gpt``.

    ``pad_vocab_to_multiple=128`` pads the embedding/vocab dimension with
    zero rows for MXU-friendly shapes (padded ids are never produced by a
    tokenizer, so logits for them are inert).

    Works fully offline: pass ``GPT2LMHeadModel(GPT2Config(...))`` built
    locally, or any mapping of GPT-2 state-dict names to arrays.
    """
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        if cfg is None and hasattr(model_or_state_dict, "config"):
            cfg = gpt_config_from_hf(model_or_state_dict.config)
    else:
        sd = dict(model_or_state_dict)
    # accept both bare GPT2Model ("h.0...") and LMHead ("transformer.h.0...")
    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    def get(name):
        return _np(sd[prefix + name])

    wte = get("wte.weight")
    wpe = get("wpe.weight")
    vocab, d = wte.shape
    if cfg is None:
        n_layers = 1 + max(
            int(k.split(".")[1 if not prefix else 2])
            for k in sd
            if ".h." in ("." + k) or k.startswith("h.")
        )
        raise ValueError(
            "pass cfg= or a model with .config (cannot infer n_heads from a "
            f"state dict; saw {n_layers} layers)"
        )
    if pad_vocab_to_multiple > 1:
        target = -(-vocab // pad_vocab_to_multiple) * pad_vocab_to_multiple
        if target != vocab:
            wte = np.concatenate([wte, np.zeros((target - vocab, d), np.float32)])
            import dataclasses

            cfg = dataclasses.replace(cfg, vocab_size=target)
    L = cfg.n_layers

    def stack(name):
        return np.stack([get(f"h.{i}.{name}") for i in range(L)])

    blocks = {
        "ln1": {"scale": stack("ln_1.weight"), "bias": stack("ln_1.bias")},
        "attn_qkv": {"kernel": stack("attn.c_attn.weight"), "bias": stack("attn.c_attn.bias")},
        "attn_out": {"kernel": stack("attn.c_proj.weight"), "bias": stack("attn.c_proj.bias")},
        "ln2": {"scale": stack("ln_2.weight"), "bias": stack("ln_2.bias")},
        "mlp_in": {"kernel": stack("mlp.c_fc.weight"), "bias": stack("mlp.c_fc.bias")},
        "mlp_out": {"kernel": stack("mlp.c_proj.weight"), "bias": stack("mlp.c_proj.bias")},
    }
    params = {
        "embed": {"tokens": wte, "pos": wpe},
        "blocks": blocks,
        "ln_f": {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")},
        # HF ties lm_head to wte (vocab, d); our head is (d, vocab)
        "lm_head": {"kernel": np.ascontiguousarray(wte.T)},
    }
    import jax
    import jax.numpy as jnp

    params = jax.tree.map(jnp.asarray, params)
    return cfg, params
