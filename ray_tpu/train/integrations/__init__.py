"""Framework integrations for ray_tpu.train.

TPU-native counterpart of the reference's trainer integrations
(``python/ray/train/huggingface/``, ``train/lightning/``, torch utils in
``train/torch/train_loop_utils.py``): instead of wrapping torch models in
DDP/FSDP, these adapters move weights and checkpoints between external
ecosystems (HuggingFace transformers, orbax, flax) and the pjit-sharded
JAX training stack.
"""

from ray_tpu.train.integrations.huggingface import (  # noqa: F401
    gpt_config_from_hf,
    gptj_config_from_hf,
    load_hf_gpt2,
    load_hf_gptj,
)
from ray_tpu.train.integrations.flax_bridge import (  # noqa: F401
    build_flax_train_step,
    flax_sharding_rules,
)
from ray_tpu.train.integrations.orbax import (  # noqa: F401
    load_pytree_checkpoint,
    save_pytree_checkpoint,
)
