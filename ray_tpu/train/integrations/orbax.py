"""Orbax-backed pytree checkpointing.

The reference persists checkpoints as directories on a ``pyarrow.fs``
(``python/ray/train/_checkpoint.py:56``, storage in
``train/_internal/storage.py``). For JAX pytrees the TPU-native serializer is
orbax: sharded-array aware, async-capable, restores under a different mesh
(the multihost checkpoint story). These helpers bridge orbax directories and
``ray_tpu.train.Checkpoint`` so ``session.report(checkpoint=...)`` can carry
sharded model state.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ray_tpu.train._checkpoint import Checkpoint

_SUBDIR = "pytree"


def save_pytree_checkpoint(state: Any, path: str) -> Checkpoint:
    """Write ``state`` (a pytree of arrays/scalars) to ``path`` with orbax
    and return a train ``Checkpoint`` handle for ``session.report``.
    ``path`` may be a pyarrow.fs URI — orbax writes to a local stage and the
    result is uploaded through the storage layer."""
    import orbax.checkpoint as ocp

    from ray_tpu.train import _storage

    if _storage.is_uri(path):
        import tempfile

        with tempfile.TemporaryDirectory(prefix="orbax_stage_") as stage:
            save_pytree_checkpoint(state, stage)
            return Checkpoint(stage).to_uri(path)

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, _SUBDIR), state, force=True)
    return Checkpoint.from_directory(path)


def load_pytree_checkpoint(
    checkpoint: "Checkpoint | str", target: Optional[Any] = None
) -> Any:
    """Restore a pytree saved by :func:`save_pytree_checkpoint`.

    ``target`` (a pytree of like-shaped arrays, e.g. from ``jax.eval_shape``
    or a freshly-initialized model) restores with matching structure and
    sharding; without it orbax returns the raw saved tree.
    """
    import orbax.checkpoint as ocp

    if isinstance(checkpoint, str):
        checkpoint = Checkpoint(checkpoint)
    with checkpoint.as_directory() as path:
        item = os.path.join(os.path.abspath(path), _SUBDIR)
        with ocp.PyTreeCheckpointer() as ckptr:
            if target is not None:
                return ckptr.restore(item, item=target)
            return ckptr.restore(item)
