"""Flax/linen ecosystem bridge: train ANY linen module on the sharded stack.

Reference capability: the reference's trainer integrations wrap external
frameworks' models for its distributed loop (Lightning/Accelerate/DeepSpeed
in ``python/ray/train/lightning/``, ``huggingface/``). The JAX-ecosystem
analog is flax/linen (t5x, MaxText, most open JAX models): this bridge
takes a ``linen.Module`` + loss and returns the same ``(init_fn, step_fn)``
contract ``parallel.train_step.build_train_step`` produces — jitted
fwd+bwd+optimizer with ZeRO-style sharding — so a flax model drops into
``JaxTrainer`` / bench loops unchanged.

Sharding: flax trees don't follow ``models.gpt``'s path conventions, so
specs come from :func:`flax_sharding_rules` — a size-aware heuristic
(shard each large parameter's LARGEST axis over ``fsdp``, replicate small
tensors) with an ``overrides`` escape hatch of regex → PartitionSpec for
models that need exact Megatron-style placement.
"""

from __future__ import annotations

import re
from typing import TYPE_CHECKING, Any, Callable, Optional

if TYPE_CHECKING:  # pragma: no cover - typing only
    from jax.sharding import PartitionSpec as P


def flax_sharding_rules(
    params: Any,
    min_shard_size: int = 2**16,
    overrides: Optional[list[tuple[str, "P"]]] = None,
) -> Any:
    """PartitionSpec pytree for an arbitrary flax param tree.

    * a path matching an ``overrides`` regex takes that spec verbatim;
    * parameters with ``size >= min_shard_size`` shard their largest axis
      over ``fsdp`` (ZeRO-style: weights and their Adam moments scatter);
    * everything else replicates (biases, scales, small embeddings).
    """
    import jax  # lazy, like the sibling integrations: the package must
    from jax.sharding import PartitionSpec as P  # import without jax

    overrides = overrides or []

    def one(path, leaf):
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        for pattern, spec in overrides:
            if re.search(pattern, key):
                return spec
        shape = getattr(leaf, "shape", ())
        if not shape or leaf.size < min_shard_size:
            return P()
        axis = max(range(len(shape)), key=lambda i: shape[i])
        spec = [None] * len(shape)
        spec[axis] = "fsdp"
        return P(*spec)

    return jax.tree_util.tree_map_with_path(one, params)


def build_flax_train_step(
    module: Any,
    loss_fn: Callable[[Callable, Any, Any], jax.Array],
    optimizer: Any,
    mesh,
    sample_batch: Any,
    rngs: Optional[dict] = None,
    min_shard_size: int = 2**16,
    sharding_overrides: Optional[list[tuple[str, "P"]]] = None,
):
    """(init_fn, step_fn) for a linen module on a device mesh.

    Args:
      module: a ``flax.linen.Module``.
      loss_fn: ``loss_fn(apply_fn, params, batch) -> scalar`` — apply_fn is
        ``module.apply`` partially applied with nothing, so the user calls
        ``apply_fn({"params": params}, ...)`` exactly as in plain flax.
      optimizer: any optax ``GradientTransformation``.
      mesh: a ``jax.sharding.Mesh`` with (at least) an ``fsdp`` axis.
      sample_batch: one batch (host values) used only to trace ``init``.
      rngs: extra PRNG streams for init (dropout etc.).

    Returns:
      ``init_fn() -> TrainState`` (params initialized ON the mesh with the
      heuristic shardings) and ``step_fn(state, batch) -> (state, loss)``
      (jitted, donated, batch sharded over dp+fsdp).
    """
    import jax

    from ray_tpu.parallel.train_step import (
        TrainState,
        make_step_fn,
        profile_step_fn,
        shard_train_state,
    )

    def model_loss(params, batch):
        return loss_fn(module.apply, params, batch)

    def init_fn() -> TrainState:
        import numpy as np

        init_rngs = {"params": jax.random.PRNGKey(0), **(rngs or {})}
        host_batch = jax.tree.map(np.asarray, sample_batch)
        params = module.init(init_rngs, host_batch)["params"]
        p_specs = flax_sharding_rules(
            params, min_shard_size=min_shard_size,
            overrides=sharding_overrides,
        )
        # placement + step wiring are the SAME code build_train_step uses —
        # only the sharding-rule source differs
        return shard_train_state(params, p_specs, optimizer, mesh)

    # profiled: per-step wall time + runtime retrace detection ride the
    # train plane's metrics (device_step_seconds{site=train_step}); the
    # raw jitted step stays reachable via step_fn.__wrapped__
    return init_fn, profile_step_fn(make_step_fn(model_loss, optimizer, mesh))
