"""WorkerGroup: the N train-worker actors.

Reference: ``python/ray/train/_internal/worker_group.py:102`` (actor group)
+ ``backend_executor.py:358`` (rank/world-size env). A ray_tpu train worker
is a *host*: one JAX process driving all local chips, so ranks here are host
ranks (jax process indices), not device ranks.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.train._session import TrainContext, _TrainSession


class RayTrainWorker:
    """Actor body. Holds the running train session for this worker."""

    def __init__(self):
        self.session: Optional[_TrainSession] = None

    def node_info(self) -> dict:
        import ray_tpu as rt

        ctx = rt.get_runtime_context()
        try:
            ip = socket.gethostbyname(socket.gethostname())
        except OSError:
            ip = "127.0.0.1"
        return {
            "node_id": ctx.get_node_id(),
            "pid": os.getpid(),
            "hostname": socket.gethostname(),
            "ip": ip,
        }

    def set_env(self, env: dict[str, str]) -> None:
        os.environ.update(env)

    def execute(self, fn: Callable, *args, **kwargs) -> Any:
        """Run an arbitrary function in the worker process (reference:
        WorkerGroup.execute)."""
        return fn(*args, **kwargs)

    def start_training(
        self,
        train_fn: Callable,
        config: Optional[dict],
        context: TrainContext,
        checkpoint,
        dataset_shards: Optional[dict],
    ) -> bool:
        assert self.session is None or self.session.finished, "training already running"
        self.session = _TrainSession(train_fn, config, context, checkpoint, dataset_shards)
        self.session.start()
        return True

    def next_result(self, timeout: float = 1.0):
        """One session event or None: ('result', metrics, ckpt) |
        ('done', ret, None) | ('error', exc, tb)."""
        if self.session is None:
            return ("error", RuntimeError("no session"), None)
        ev = self.session.next(timeout=timeout)
        if ev is not None and ev[0] in ("done", "error"):
            self.session.finished = True
        return ev

    def ack_result(self) -> bool:
        """Driver committed the last reported result; unblock report()."""
        if self.session is not None:
            self.session.ack_event.set()
        return True

    def shutdown(self) -> bool:
        return True


class WorkerGroup:
    """Spawns and addresses the worker actors."""

    def __init__(
        self,
        num_workers: int,
        resources_per_worker: dict[str, float],
        placement_strategy: str = "PACK",
        max_restarts: int = 0,
    ):
        from ray_tpu.util.placement_group import placement_group
        from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

        self.num_workers = num_workers
        self.pg = placement_group([dict(resources_per_worker)] * num_workers, strategy=placement_strategy)
        self.pg.wait(timeout_seconds=60.0)
        cls = ray_tpu.remote(
            num_cpus=0,
            max_restarts=0,
        )(RayTrainWorker)
        self.workers = [
            cls.options(
                resources={k: v for k, v in resources_per_worker.items()},
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    self.pg, placement_group_bundle_index=i
                ),
            ).remote()
            for i in range(num_workers)
        ]
        infos = ray_tpu.get([w.node_info.remote() for w in self.workers])
        # Host ranks: stable sort by (node, pid); local ranks count within node.
        order = sorted(range(num_workers), key=lambda i: (infos[i]["node_id"], infos[i]["pid"]))
        self.ranks = [0] * num_workers
        for rank, idx in enumerate(order):
            self.ranks[idx] = rank
        self.infos = infos
        self.local_ranks = [0] * num_workers
        self.node_ranks = [0] * num_workers
        per_node: dict[str, int] = {}
        node_idx: dict[str, int] = {}
        for rank, idx in enumerate(order):
            nid = infos[idx]["node_id"]
            if nid not in node_idx:
                node_idx[nid] = len(node_idx)
            self.local_ranks[idx] = per_node.get(nid, 0)
            per_node[nid] = per_node.get(nid, 0) + 1
            self.node_ranks[idx] = node_idx[nid]
        self.local_world_sizes = [per_node[infos[i]["node_id"]] for i in range(num_workers)]

    def context_for(self, i: int, experiment: str = "train", trial: str = "trial") -> TrainContext:
        return TrainContext(
            world_size=self.num_workers,
            world_rank=self.ranks[i],
            local_rank=self.local_ranks[i],
            local_world_size=self.local_world_sizes[i],
            node_rank=self.node_ranks[i],
            experiment_name=experiment,
            trial_name=trial,
        )

    def execute(self, fn: Callable, *args, **kwargs) -> list:
        return ray_tpu.get([w.execute.remote(fn, *args, **kwargs) for w in self.workers])

    def execute_single(self, i: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(self.workers[i].execute.remote(fn, *args, **kwargs))

    def set_env(self, envs: "list[dict[str, str]]") -> None:
        ray_tpu.get([w.set_env.remote(e) for w, e in zip(self.workers, envs)])

    def shutdown(self):
        from ray_tpu._private.log_util import warn_throttled

        try:
            ray_tpu.get([w.shutdown.remote() for w in self.workers], timeout=5.0)
        except Exception:
            pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception as e:
                # best-effort teardown, but not silent: a kill that fails for
                # any reason other than "already dead" means leaked workers
                warn_throttled("train worker group teardown", e)
        from ray_tpu.util.placement_group import remove_placement_group

        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
