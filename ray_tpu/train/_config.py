"""Run/scaling/failure/checkpoint configs.

Counterparts of the reference's ``python/ray/air/config.py``:
``ScalingConfig`` :101, ``FailureConfig`` :377, ``CheckpointConfig`` :427,
``RunConfig`` :576 — reshaped for TPU: a worker is a *host* driving all its
local chips through one JAX process (multi-controller SPMD), not a
one-process-per-device rank.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many train workers (hosts) and what each one holds.

    ``num_workers`` is the number of JAX processes (= TPU hosts). Chips are
    not divided among workers on a host: each worker drives all chips the
    scheduler gives it via one device mesh.
    """

    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # accepted for API parity; TPU path is use_tpu
    resources_per_worker: Optional[dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None  # e.g. "v5e-8" (advisory label)

    def worker_resources(self) -> dict[str, float]:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
            res.setdefault("CPU", 1.0)
            return res
        res = {"CPU": 1.0}
        if self.use_tpu:
            res["TPU"] = 1.0
        if self.use_gpu:
            res["GPU"] = 1.0
        return res

    @property
    def total_resources(self) -> dict[str, float]:
        per = self.worker_resources()
        return {k: v * self.num_workers for k, v in per.items()}


@dataclasses.dataclass
class FailureConfig:
    """Trial-level retry budget (reference ``air/config.py:377``).

    ``max_failures=-1`` retries forever; 0 disables retries."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """Keep-N checkpointing policy (reference ``air/config.py:427``)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclasses.dataclass
class RunConfig:
    """Where results live + failure/checkpoint policy
    (reference ``air/config.py:576``)."""

    name: Optional[str] = None
    #: local path OR pyarrow.fs URI (``s3://…``, ``gs://…``, ``file:///…``)
    #: — reference ``RunConfig.storage_path`` (``train/_internal/storage.py``)
    storage_path: Optional[str] = None
    #: custom ``pyarrow.fs.FileSystem`` (tests / exotic backends); when set,
    #: ``storage_path`` is interpreted as a path INSIDE this filesystem
    storage_filesystem: Optional[object] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    verbose: int = 1
    log_to_file: bool = False

    def resolved_storage_path(self) -> str:
        if self.storage_filesystem is not None:
            # fs-internal path (may legitimately be "" = the fs root)
            return str(self.storage_path or "")
        base = self.storage_path or os.environ.get(
            "RAY_TPU_STORAGE_PATH", os.path.expanduser("~/ray_tpu_results")
        )
        from ray_tpu.train._storage import is_uri

        if is_uri(base):
            return str(base)  # URI: never abspath
        return os.path.abspath(os.path.expanduser(base))


@dataclasses.dataclass
class JaxConfig:
    """Backend config for JAX process-group bring-up (the reference's
    ``train/torch/config.py:47-91`` runs ``dist.init_process_group``; the TPU
    equivalent is ``jax.distributed.initialize`` against a coordinator, after
    which all hosts share one global device mesh)."""

    coordinator_port: int = 8476
    # When True (multi-host TPU pods), workers call
    # jax.distributed.initialize(coordinator, num_processes, process_id).
    # Single-host runs (and CPU test meshes) skip it.
    init_distributed: bool = False
    mesh_shape: Optional[dict[str, int]] = None  # dp/fsdp/sp/tp sizes

    def backend_name(self) -> str:
        return "jax"


def dataclass_repr(obj: Any) -> str:
    fields = dataclasses.fields(obj)
    parts = [f"{f.name}={getattr(obj, f.name)!r}" for f in fields]
    return f"{type(obj).__name__}({', '.join(parts)})"
