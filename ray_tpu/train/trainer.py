"""Trainers: JaxTrainer / DataParallelTrainer.

Reference shape: ``python/ray/train/data_parallel_trainer.py:432``
(``training_loop`` drives BackendExecutor + forwards ``session.report``
results) and ``base_trainer.py:581`` (``fit``). Failure semantics follow
``FailureConfig(max_failures)``: on a worker failure the whole group is torn
down and relaunched from the latest committed checkpoint — on TPU a lost
host kills the mesh, so group-restart-from-checkpoint is the *only* sound
recovery (SURVEY §7 "SPMD-vs-actor impedance"), unlike per-rank NCCL retry.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._backend_executor import (
    BackendExecutor,
    JaxBackend,
    TrainingWorkerError,
)
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._checkpoint_manager import CheckpointManager
from ray_tpu.train._config import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)


@dataclasses.dataclass
class Result:
    """Reference: ``ray.air.Result``."""

    metrics: Optional[dict]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: Optional[list] = None

    @property
    def best_checkpoints(self):
        return [(self.checkpoint, self.metrics)] if self.checkpoint else []


class DataParallelTrainer:
    """Runs ``train_loop_per_worker`` on N workers (hosts) in lockstep."""

    _backend_cls = JaxBackend

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[dict] = None,
        backend_config: Optional[JaxConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        metadata: Optional[dict] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.backend_config = backend_config
        self.resume_from_checkpoint = resume_from_checkpoint
        self.metadata = metadata or {}

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        from ray_tpu.train import _storage as storage_mod
        from ray_tpu.train._storage import StorageContext

        run_name = self.run_config.name or f"{type(self).__name__}_{int(time.time())}"
        storage_path = self.run_config.resolved_storage_path()
        storage_fs = self.run_config.storage_filesystem
        # URI / custom-fs storage persists through pyarrow.fs (reference:
        # StorageContext, train/_internal/storage.py); plain local paths keep
        # the direct-directory layout
        use_storage = storage_fs is not None or storage_mod.is_uri(storage_path)
        if use_storage:
            storage = StorageContext(
                storage_path, run_name, "trial_0", storage_filesystem=storage_fs
            )
            trial_dir = os.path.join(
                os.path.expanduser("~/ray_tpu_results"), "_staging", run_name, "trial_0"
            )
            result_path = storage.uri_for("")
        else:
            storage = None
            exp_dir = os.path.join(storage_path, run_name)
            trial_dir = os.path.join(exp_dir, "trial_0")
            result_path = trial_dir
        os.makedirs(trial_dir, exist_ok=True)
        failure = self.run_config.failure_config or FailureConfig()
        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        manager = CheckpointManager(trial_dir, ckpt_cfg, storage=storage)

        failures_left = failure.max_failures
        start_ckpt = self.resume_from_checkpoint
        last_metrics: Optional[dict] = None
        history: list = []
        error: Optional[BaseException] = None

        while True:
            executor = BackendExecutor(
                self.scaling_config,
                self._backend_cls(self.backend_config),
                experiment_name=run_name,
            )
            try:
                executor.start()
                executor.start_training(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    manager.latest() or start_ckpt,
                    self._dataset_splitter(),
                )
                # history is shared so results committed before a mid-run
                # worker failure survive the restart
                last_metrics = self._result_loop(executor, manager, history)
                error = None
                break
            except TrainingWorkerError as e:
                history_error = e
                if failures_left == 0:
                    error = e
                    break
                if failures_left > 0:
                    failures_left -= 1
                if self.run_config.verbose:
                    print(
                        f"[ray_tpu.train] worker failure ({history_error}); restarting "
                        f"group from {manager.latest()} "
                        f"({failures_left if failures_left >= 0 else 'inf'} retries left)"
                    )
            finally:
                executor.shutdown()

        result = Result(
            metrics=last_metrics,
            checkpoint=manager.best(),
            path=result_path,
            error=error,
            metrics_history=history,
        )
        if error is not None and not isinstance(error, TrainingWorkerError):
            raise error
        return result

    def _result_loop(self, executor: BackendExecutor, manager: CheckpointManager, history: list):
        """Consume lockstep events until every worker's loop returns."""
        last_metrics = None
        done = [False] * self.scaling_config.num_workers
        rank0 = executor.wg.ranks.index(0)  # worker index holding world rank 0
        while not all(done):
            events = executor.next_results(done_mask=done)
            report_metrics = None
            report_ckpt = None
            for i, ev in enumerate(events):
                if ev is None:
                    continue
                kind = ev[0]
                if kind == "done":
                    done[i] = True
                elif kind == "result":
                    _, metrics, ckpt = ev
                    if i == rank0 or report_metrics is None:
                        report_metrics = metrics
                    if ckpt is not None and (i == rank0 or report_ckpt is None):
                        report_ckpt = ckpt  # rank-0's checkpoint wins
            if report_metrics is not None:
                committed = None
                if report_ckpt is not None:
                    committed = manager.commit(report_ckpt, report_metrics)
                last_metrics = report_metrics
                history.append({"metrics": report_metrics, "checkpoint": committed})
            # ack unblocks the workers' report() only after the commit above
            import ray_tpu

            acks = [
                executor.wg.workers[i].ack_result.remote()
                for i, ev in enumerate(events)
                if ev is not None and ev[0] == "result"
            ]
            if acks:
                try:
                    ray_tpu.get(acks)
                except Exception as e:
                    from ray_tpu.train._backend_executor import TrainingWorkerError

                    raise TrainingWorkerError(-1, e, None) from e
        return last_metrics

    def _dataset_splitter(self) -> Optional[Callable[[int, int], dict]]:
        if not self.datasets:
            return None
        datasets = self.datasets

        def split(rank: int, world: int) -> dict:
            shards = {}
            for name, ds in datasets.items():
                if hasattr(ds, "streaming_split_shard"):
                    shards[name] = ds.streaming_split_shard(rank, world)
                elif hasattr(ds, "split"):
                    shards[name] = ds.split(world)[rank]
                else:
                    shards[name] = _IterShard(ds, rank, world)
            return shards

        return split

    def as_trainable(self):
        """Adapter so a trainer runs as a Tune trainable (reference:
        BaseTrainer.fit wraps itself in a 1-trial Tune run,
        ``base_trainer.py:581-645``; we invert — Tune wraps the trainer)."""
        trainer = self

        def trainable(config):
            from ray_tpu import tune

            merged = dict(trainer.train_loop_config or {})
            merged.update(config or {})
            t = type(trainer)(
                trainer.train_loop_per_worker,
                train_loop_config=merged,
                scaling_config=trainer.scaling_config,
                run_config=trainer.run_config,
                datasets=trainer.datasets,
                backend_config=trainer.backend_config,
            )
            result = t.fit()
            if result.metrics:
                tune.report(result.metrics)

        return trainable


class _IterShard:
    """Round-robin shard over a plain iterable (lists, generators-factories)."""

    def __init__(self, data, rank: int, world: int):
        self.data = data
        self.rank = rank
        self.world = world

    def __iter__(self):
        for i, item in enumerate(self.data):
            if i % self.world == self.rank:
                yield item

    def iter_batches(self, batch_size: int = 32):
        batch = []
        for item in self:
            batch.append(item)
            if len(batch) == batch_size:
                yield batch
                batch = []
        if batch:
            yield batch


class JaxTrainer(DataParallelTrainer):
    """Flagship trainer: SPMD JAX training over the worker group's mesh.

    The torch trainer's ``prepare_model`` (DDP/FSDP wrapping,
    ``train/torch/train_loop_utils.py:158-186``) has no TPU equivalent
    object: sharding is declared via ``ray_tpu.parallel`` rule tables and
    compiled by XLA. The train loop typically:

        mesh = ray_tpu.parallel.make_mesh(MeshConfig(dp=-1, fsdp=1, tp=1))
        init_fn, step_fn = build_train_step(loss, optimizer, mesh)
        state = init_fn(params)
        for batch in it: state, loss = step_fn(state, batch)
        ray_tpu.train.report({"loss": float(loss)}, checkpoint=...)
    """
