"""Checkpoint: a directory handle with metadata.

Reference: ``python/ray/train/_checkpoint.py:56`` — a Checkpoint is a
directory on a filesystem, never a live object graph; frameworks serialize
into it (here: orbax/msgpack/npz for JAX pytrees).
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
from typing import Any, Iterator, Optional

_METADATA_FILE = ".ray_tpu_checkpoint.json"


class Checkpoint:
    """A handle to a checkpoint directory on the local/shared filesystem."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint contents into ``path`` (or a fresh temp dir)."""
        dest = path or tempfile.mkdtemp(prefix="ckpt_")
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents. Local
        checkpoints are yielded as-is (zero-copy)."""
        yield self.path

    def get_metadata(self) -> dict:
        f = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(f):
            with open(f) as fp:
                return json.load(fp)
        return {}

    def set_metadata(self, metadata: dict) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as fp:
            json.dump(metadata, fp)

    def update_metadata(self, metadata: dict) -> None:
        m = self.get_metadata()
        m.update(metadata)
        self.set_metadata(m)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self):
        return hash(self.path)


def save_pytree(tree: Any, path: str, *, step: Optional[int] = None) -> Checkpoint:
    """Serialize a JAX pytree into ``path`` and return a Checkpoint.

    Uses numpy .npz of flattened leaves + a JSON treedef — robust, fast, no
    format churn. (Orbax integration lives in ray_tpu.train.orbax_utils for
    async multihost checkpointing of sharded arrays.)
    """
    import jax
    import numpy as np

    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    np.savez(
        os.path.join(path, "pytree.npz"),
        **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )
    with open(os.path.join(path, "treedef.json"), "w") as fp:
        json.dump({"n_leaves": len(leaves), "step": step}, fp)
    import pickle

    with open(os.path.join(path, "treedef.pkl"), "wb") as fp:
        pickle.dump(treedef, fp)
    ckpt = Checkpoint(path)
    if step is not None:
        ckpt.update_metadata({"step": step})
    return ckpt


def load_pytree(checkpoint: "Checkpoint | str") -> Any:
    """Inverse of :func:`save_pytree`; leaves come back as numpy arrays
    (device placement/sharding is the caller's job via device_put)."""
    import pickle

    import numpy as np

    path = checkpoint.path if isinstance(checkpoint, Checkpoint) else checkpoint
    with open(os.path.join(path, "treedef.pkl"), "rb") as fp:
        treedef = pickle.load(fp)
    data = np.load(os.path.join(path, "pytree.npz"))
    leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)
