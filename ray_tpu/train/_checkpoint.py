"""Checkpoint: a directory handle with metadata, local or on ``pyarrow.fs``.

Reference: ``python/ray/train/_checkpoint.py:56`` — a Checkpoint is a
directory on a filesystem (local, S3, GS, NFS — resolved via pyarrow.fs),
never a live object graph; frameworks serialize into it (here: orbax/npz for
JAX pytrees). ``from_uri/to_uri`` mirror the reference's cloud round-trip.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
from typing import Any, Iterator, Optional

from ray_tpu.train import _storage

_METADATA_FILE = ".ray_tpu_checkpoint.json"


class Checkpoint:
    """A handle to a checkpoint directory.

    ``path`` may be a local directory, a URI (``s3://…``, ``gs://…``,
    ``file:///…``), or an fs-internal path paired with an explicit
    ``filesystem`` (reference: ``Checkpoint(path, filesystem)``).
    """

    def __init__(self, path: str, filesystem=None):
        if filesystem is None and not _storage.is_uri(path):
            self.path = os.path.abspath(path)
            self.filesystem = None
            self._fs_path = self.path
        else:
            self.path = str(path)
            fs, fs_path = _storage.get_fs_and_path(path, filesystem)
            self.filesystem = fs
            self._fs_path = fs_path

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Handle to a checkpoint already persisted at ``uri``
        (reference: ``Checkpoint.from_uri``)."""
        return cls(uri)

    def to_uri(self, uri: str) -> "Checkpoint":
        """Upload this (local) checkpoint to ``uri`` and return the remote
        handle (reference: ``Checkpoint.to_uri``)."""
        fs, fs_path = _storage.get_fs_and_path(uri)
        with self.as_directory() as local:
            _storage.upload_dir(fs, fs_path, local)
        return Checkpoint(uri)

    # -- local access ------------------------------------------------------
    @property
    def _is_remote(self) -> bool:
        if self.filesystem is None:
            return False  # keep purely-local flows pyarrow-free
        from pyarrow import fs as pafs

        return not isinstance(self.filesystem, pafs.LocalFileSystem)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint contents into ``path`` (or a fresh temp dir)."""
        dest = path or tempfile.mkdtemp(prefix="ckpt_")
        os.makedirs(dest, exist_ok=True)
        if self._is_remote:
            _storage.download_dir(self.filesystem, self._fs_path, dest)
        else:
            shutil.copytree(self._fs_path, dest, dirs_exist_ok=True)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Yield a local directory with the checkpoint contents. Local
        checkpoints are yielded as-is (zero-copy); remote ones download to a
        temp dir that is removed afterwards."""
        if not self._is_remote:
            yield self._fs_path
            return
        dest = self.to_directory()
        try:
            yield dest
        finally:
            shutil.rmtree(dest, ignore_errors=True)

    # -- metadata ----------------------------------------------------------
    def get_metadata(self) -> dict:
        if self._is_remote:
            meta = _storage.fs_join(self._fs_path, _METADATA_FILE)
            if _storage.exists(self.filesystem, meta):
                return _storage.read_json(self.filesystem, meta)
            return {}
        f = os.path.join(self._fs_path, _METADATA_FILE)
        if os.path.exists(f):
            with open(f) as fp:
                return json.load(fp)
        return {}

    def set_metadata(self, metadata: dict) -> None:
        if self._is_remote:
            _storage.write_json(
                self.filesystem, _storage.fs_join(self._fs_path, _METADATA_FILE), metadata
            )
            return
        with open(os.path.join(self._fs_path, _METADATA_FILE), "w") as fp:
            json.dump(metadata, fp)

    def update_metadata(self, metadata: dict) -> None:
        m = self.get_metadata()
        m.update(metadata)
        self.set_metadata(m)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self):
        return hash(self.path)


def save_pytree(tree: Any, path: str, *, step: Optional[int] = None) -> Checkpoint:
    """Serialize a JAX pytree into ``path`` and return a Checkpoint.

    Uses numpy .npz of flattened leaves + a JSON treedef — robust, fast, no
    format churn. (Orbax integration lives in ray_tpu.train.orbax_utils for
    async multihost checkpointing of sharded arrays.) ``path`` may be a URI:
    the pytree is staged locally and uploaded.
    """
    import jax
    import numpy as np

    if _storage.is_uri(path):
        with tempfile.TemporaryDirectory(prefix="ckpt_stage_") as stage:
            save_pytree(tree, stage, step=step)
            return Checkpoint(stage).to_uri(path)

    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    np.savez(
        os.path.join(path, "pytree.npz"),
        **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )
    with open(os.path.join(path, "treedef.json"), "w") as fp:
        json.dump({"n_leaves": len(leaves), "step": step}, fp)
    import pickle

    with open(os.path.join(path, "treedef.pkl"), "wb") as fp:
        pickle.dump(treedef, fp)
    ckpt = Checkpoint(path)
    if step is not None:
        ckpt.update_metadata({"step": step})
    return ckpt


def load_pytree(checkpoint: "Checkpoint | str") -> Any:
    """Inverse of :func:`save_pytree`; leaves come back as numpy arrays
    (device placement/sharding is the caller's job via device_put). Accepts
    a Checkpoint (local or remote), a local path, or a URI."""
    import pickle

    import numpy as np

    if isinstance(checkpoint, str):
        checkpoint = Checkpoint(checkpoint)
    with checkpoint.as_directory() as path:
        with open(os.path.join(path, "treedef.pkl"), "rb") as fp:
            treedef = pickle.load(fp)
        with np.load(os.path.join(path, "pytree.npz")) as data:
            leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)
