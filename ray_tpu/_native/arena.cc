// Shared-memory object arena — the native core of the host object store.
//
// TPU-native counterpart of the reference's plasma store
// (src/ray/object_manager/plasma/store.h:55, dlmalloc over mmap +
// eviction_policy.cc pinning): one POSIX shm segment per host holding a
// boundary-tag heap, shared by every local process. Differences from plasma
// are deliberate TPU-first simplifications:
//
//   * no store daemon and no socket protocol — producers allocate directly
//     under a process-shared robust mutex; consumers map the segment once
//     and read zero-copy (plasma's create/seal/get round-trips disappear),
//   * object lifetime stays with the Python head (it calls free); the arena
//     only enforces *safety*: each block carries a generation + pin count so a
//     reader can atomically pin-if-still-alive, and frees of pinned blocks
//     defer until the last unpin (plasma: client refcount pinning).
//
// Layout:  [ArenaHeader][Block payload][Block payload]...
// All offsets are from the segment base; payload offsets are what the API
// hands out. Blocks are 64-byte aligned; physical neighbours found via
// size (forward) and prev_off (backward) for O(1) free-time coalescing.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Bumped (v2) when the segment layout gained the live-header bitmap, so a
// stale pre-bitmap segment left in /dev/shm can never be attached.
constexpr uint64_t kMagic = 0x52544e4152454e42ull;  // "RTNARENB"
constexpr uint64_t kAlign = 64;

// Block.state word: [ generation:43 | zombie:1 | pins:20 ]
constexpr uint64_t kPinMask = (1ull << 20) - 1;
constexpr uint64_t kZombieBit = 1ull << 20;
constexpr uint64_t kGenShift = 21;

inline uint64_t align_up(uint64_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

struct Block {
  uint64_t size;      // payload capacity, multiple of 64
  uint64_t prev_off;  // offset of physical predecessor's Block (0 = first)
  uint64_t is_free;   // 1 = on free path (not allocated)
  std::atomic<uint64_t> state;  // generation | zombie | pin count
  uint8_t _pad[kAlign - 32];
};
static_assert(sizeof(Block) == kAlign, "block header must be one cache line");

struct ArenaHeader {
  uint64_t magic;
  uint64_t size;        // whole segment size
  uint64_t first_block; // offset of the first Block
  uint64_t bitmap_off;  // offset of the live-header bitmap (1 bit / 64B line)
  std::atomic<uint64_t> used;      // allocated payload bytes
  std::atomic<uint64_t> n_objects; // live allocations
  std::atomic<uint64_t> gen;       // generation counter
  pthread_mutex_t lock; // process-shared, robust
  uint8_t _pad[256];
};

struct Handle {
  uint8_t* base;
  uint64_t size;
};

inline ArenaHeader* hdr(Handle* h) { return reinterpret_cast<ArenaHeader*>(h->base); }
inline Block* block_at(Handle* h, uint64_t off) {
  return reinterpret_cast<Block*>(h->base + off);
}
// Payload offset <-> block offset.
inline uint64_t payload_of(uint64_t block_off) { return block_off + sizeof(Block); }
inline uint64_t block_of(uint64_t payload_off) { return payload_off - sizeof(Block); }
inline uint64_t next_off(Handle* h, uint64_t off) {
  Block* b = block_at(h, off);
  uint64_t n = off + sizeof(Block) + b->size;
  return n >= hdr(h)->size ? 0 : n;
}

// Live-header bitmap: bit (block_off / kAlign) is set iff that 64-byte line
// is the header of a currently-ALLOCATED block. Mutated and read only under
// the arena mutex, so plain (non-atomic) words suffice. This is what lets
// rta_pin reject a stale payload offset that, after a free + coalesce/split,
// now lands inside some other live object's payload — without it the
// generation check would be reading (and on a 43-bit coincidence, CASing)
// arbitrary payload bytes.
inline uint64_t* bitmap_word(Handle* h, uint64_t block_off, uint64_t* mask) {
  uint64_t idx = block_off / kAlign;
  *mask = 1ull << (idx & 63);
  return reinterpret_cast<uint64_t*>(h->base + hdr(h)->bitmap_off) + (idx >> 6);
}
inline void bitmap_set(Handle* h, uint64_t block_off) {
  uint64_t mask;
  uint64_t* w = bitmap_word(h, block_off, &mask);
  *w |= mask;
}
inline void bitmap_clear(Handle* h, uint64_t block_off) {
  uint64_t mask;
  uint64_t* w = bitmap_word(h, block_off, &mask);
  *w &= ~mask;
}
inline bool bitmap_test(Handle* h, uint64_t block_off) {
  uint64_t mask;
  uint64_t* w = bitmap_word(h, block_off, &mask);
  return (*w & mask) != 0;
}

class MutexGuard {
 public:
  explicit MutexGuard(pthread_mutex_t* m) : m_(m) {
    int rc = pthread_mutex_lock(m_);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(m_);  // holder died; state is
    // consistent by construction: allocator mutations below are ordered so a
    // torn update at worst leaks one block.
  }
  ~MutexGuard() { pthread_mutex_unlock(m_); }

 private:
  pthread_mutex_t* m_;
};

// Merge b with its physical successor if that successor is free.
void try_merge_next(Handle* h, uint64_t off) {
  uint64_t n = next_off(h, off);
  if (n == 0) return;
  Block* b = block_at(h, off);
  Block* nb = block_at(h, n);
  if (!nb->is_free) return;
  b->size += sizeof(Block) + nb->size;
  uint64_t nn = next_off(h, off);
  if (nn != 0) block_at(h, nn)->prev_off = off;
}

void free_block_locked(Handle* h, uint64_t off) {
  Block* b = block_at(h, off);
  hdr(h)->used.fetch_sub(b->size, std::memory_order_relaxed);
  hdr(h)->n_objects.fetch_sub(1, std::memory_order_relaxed);
  bitmap_clear(h, off);
  b->is_free = 1;
  try_merge_next(h, off);
  uint64_t p = b->prev_off;
  if (p != 0 && block_at(h, p)->is_free) {
    try_merge_next(h, p);
  }
}

}  // namespace

extern "C" {

// Create a fresh arena segment. Returns handle or nullptr (errno set).
void* rta_create(const char* name, uint64_t size) {
  size = align_up(size);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* h = new Handle{static_cast<uint8_t*>(base), size};
  ArenaHeader* a = hdr(h);
  a->size = size;
  a->bitmap_off = align_up(sizeof(ArenaHeader));
  // One bit per 64-byte line over the whole segment (fresh shm is
  // zero-filled, so the bitmap starts all-clear).
  uint64_t bitmap_bytes = (size / kAlign + 7) / 8;
  a->first_block = align_up(a->bitmap_off + bitmap_bytes);
  a->used.store(0);
  a->n_objects.store(0);
  a->gen.store(1);
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&a->lock, &attr);
  pthread_mutexattr_destroy(&attr);
  Block* first = block_at(h, a->first_block);
  first->size = size - a->first_block - sizeof(Block);
  first->prev_off = 0;
  first->is_free = 1;
  first->state.store(0);
  a->magic = kMagic;  // published last: attachers spin/check on magic
  return h;
}

// Attach to an existing arena. Returns handle or nullptr.
void* rta_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(ArenaHeader)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* h = new Handle{static_cast<uint8_t*>(base), static_cast<uint64_t>(st.st_size)};
  if (hdr(h)->magic != kMagic) {
    munmap(base, h->size);
    delete h;
    return nullptr;
  }
  return h;
}

// Allocate `size` payload bytes. Returns payload offset (0 = full), and the
// block's generation via *gen_out (used by readers to pin safely).
uint64_t rta_alloc(void* hv, uint64_t size, uint64_t* gen_out) {
  Handle* h = static_cast<Handle*>(hv);
  ArenaHeader* a = hdr(h);
  uint64_t need = align_up(size ? size : 1);
  MutexGuard g(&a->lock);
  uint64_t off = a->first_block;
  while (off != 0) {
    Block* b = block_at(h, off);
    if (b->is_free && b->size >= need) {
      // Split when the remainder can hold a header + one aligned line.
      if (b->size >= need + sizeof(Block) + kAlign) {
        uint64_t rest_off = off + sizeof(Block) + need;
        Block* rest = block_at(h, rest_off);
        rest->size = b->size - need - sizeof(Block);
        rest->prev_off = off;
        rest->is_free = 1;
        rest->state.store(0);
        uint64_t after = next_off(h, rest_off);
        if (after != 0) block_at(h, after)->prev_off = rest_off;
        b->size = need;
      }
      b->is_free = 0;
      uint64_t gen = a->gen.fetch_add(1, std::memory_order_relaxed) + 1;
      b->state.store(gen << kGenShift, std::memory_order_release);
      bitmap_set(h, off);
      a->used.fetch_add(b->size, std::memory_order_relaxed);
      a->n_objects.fetch_add(1, std::memory_order_relaxed);
      if (gen_out) *gen_out = gen;
      return payload_of(off);
    }
    off = next_off(h, off);
  }
  return 0;
}

// Pin a block if it is still the same allocation (generation matches and it
// is not being freed). Returns 1 on success, 0 if the object is gone.
//
// Runs under the arena mutex: the caller-supplied offset may be stale, and
// only the lock + live-header bitmap can prove it still names a block header
// (after a free + coalesce/split it could point into the middle of another
// live object's payload). Holding the lock also excludes rta_free, and the
// zombie-free path in rta_unpin needs the zombie bit (set only under this
// lock), so a plain fetch_add suffices once validation passes. Pins are
// per-get, not per-byte — the uncontended pshared mutex is noise.
int rta_pin(void* hv, uint64_t payload_off, uint64_t gen) {
  Handle* h = static_cast<Handle*>(hv);
  ArenaHeader* a = hdr(h);
  if (payload_off < sizeof(Block)) return 0;
  uint64_t boff = block_of(payload_off);
  if (boff < a->first_block || (boff % kAlign) != 0 ||
      boff + sizeof(Block) > h->size)
    return 0;
  MutexGuard g(&a->lock);
  if (!bitmap_test(h, boff)) return 0;  // not a live allocated header
  Block* b = block_at(h, boff);
  uint64_t cur = b->state.load(std::memory_order_acquire);
  if ((cur >> kGenShift) != gen || (cur & kZombieBit)) return 0;
  b->state.fetch_add(1, std::memory_order_acq_rel);
  return 1;
}

// Drop a pin. If the block was zombied (freed while pinned) and this was the
// last pin, complete the free.
int rta_unpin(void* hv, uint64_t payload_off) {
  Handle* h = static_cast<Handle*>(hv);
  Block* b = block_at(h, block_of(payload_off));
  uint64_t prev = b->state.fetch_sub(1, std::memory_order_acq_rel);
  if ((prev & kPinMask) == 1 && (prev & kZombieBit)) {
    ArenaHeader* a = hdr(h);
    MutexGuard g(&a->lock);
    // Re-check under the lock: another pinner may have raced in between.
    uint64_t cur = b->state.load(std::memory_order_acquire);
    if ((cur & kPinMask) == 0 && (cur & kZombieBit) && !b->is_free) {
      b->state.store(0, std::memory_order_release);
      free_block_locked(h, block_of(payload_off));
    }
  }
  return 0;
}

// Free an allocation. If readers hold pins, the block is zombied and the
// last unpin completes the free. Returns 0 freed now, 1 deferred, -1 gone.
// The state word is claimed by CAS: rta_unpin's fetch_sub runs without the
// mutex, so a plain load+store here could lose a concurrent unpin and free
// a block with corrupted pin bookkeeping.
int rta_free(void* hv, uint64_t payload_off, uint64_t gen) {
  Handle* h = static_cast<Handle*>(hv);
  ArenaHeader* a = hdr(h);
  MutexGuard g(&a->lock);
  Block* b = block_at(h, block_of(payload_off));
  uint64_t cur = b->state.load(std::memory_order_acquire);
  for (;;) {
    if (b->is_free || (cur >> kGenShift) != gen || (cur & kZombieBit)) return -1;
    if ((cur & kPinMask) != 0) {
      if (b->state.compare_exchange_weak(cur, cur | kZombieBit,
                                         std::memory_order_acq_rel))
        return 1;  // readers active: the last unpin completes the free
      continue;    // a pin/unpin raced in; re-evaluate
    }
    // CAS to 0 claims the block iff still exactly (gen, no pins, no zombie);
    // a concurrent pin changes the word and the CAS retries.
    if (b->state.compare_exchange_weak(cur, 0, std::memory_order_acq_rel)) {
      free_block_locked(h, block_of(payload_off));
      return 0;
    }
  }
}

uint64_t rta_used(void* hv) { return hdr(static_cast<Handle*>(hv))->used.load(); }
uint64_t rta_segment_size(void* hv) { return static_cast<Handle*>(hv)->size; }
uint64_t rta_capacity(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  return h->size - hdr(h)->first_block;
}
uint64_t rta_n_objects(void* hv) {
  return hdr(static_cast<Handle*>(hv))->n_objects.load();
}
// Base address of the mapping (payload pointers = base + payload offset).
void* rta_base(void* hv) { return static_cast<Handle*>(hv)->base; }

void rta_detach(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  munmap(h->base, h->size);
  delete h;
}

int rta_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
