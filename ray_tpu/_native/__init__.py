"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its hot runtime in C++ (plasma store, raylet, core
worker); here the native layer holds the pieces that benefit from being
native on a TPU *host* — the shared-memory object arena (``arena.cc``, the
plasma equivalent). JAX/XLA owns device compute; this code owns host memory.

No pybind11 in the image, so the ABI is plain C and the binding is ctypes.
The library is compiled on first use with g++ into a per-source-hash cached
.so; any failure (no compiler, exotic platform) degrades gracefully — callers
must treat ``load() is None`` as "native path unavailable" and fall back to
the pure-Python implementation.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_TRIED = False


def _build_dir() -> str:
    d = os.environ.get("RAY_TPU_NATIVE_BUILD_DIR") or os.path.join(
        tempfile.gettempdir(), "ray_tpu_native"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _compile(src: str, out: str) -> bool:
    cmd = [
        "g++", "-O2", "-fPIC", "-shared", "-std=c++17",
        "-o", out, src, "-lpthread", "-lrt",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0 and os.path.exists(out)


def load() -> Optional[ctypes.CDLL]:
    """Compile (once, cached by source hash) and load the native library.

    Returns None when the native path is unavailable; callers fall back.
    """
    global _LIB, _LOAD_TRIED
    if _LOAD_TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LOAD_TRIED:
            return _LIB
        _LOAD_TRIED = True
        if os.environ.get("RAY_TPU_DISABLE_NATIVE"):
            return None
        src = os.path.join(_HERE, "arena.cc")
        try:
            with open(src, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
        except OSError:
            return None
        out = os.path.join(_build_dir(), f"libray_tpu_arena-{digest}.so")
        if not os.path.exists(out):
            # build into a temp name + atomic rename so concurrent processes
            # never dlopen a half-written .so
            tmp = f"{out}.{os.getpid()}.tmp"
            if not _compile(src, tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
            os.replace(tmp, out)
        try:
            lib = ctypes.CDLL(out)
        except OSError:
            return None
        lib.rta_create.restype = ctypes.c_void_p
        lib.rta_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rta_attach.restype = ctypes.c_void_p
        lib.rta_attach.argtypes = [ctypes.c_char_p]
        lib.rta_alloc.restype = ctypes.c_uint64
        lib.rta_alloc.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.rta_pin.restype = ctypes.c_int
        lib.rta_pin.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.rta_unpin.restype = ctypes.c_int
        lib.rta_unpin.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rta_free.restype = ctypes.c_int
        lib.rta_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        for fn in ("rta_used", "rta_capacity", "rta_n_objects", "rta_segment_size"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.rta_base.restype = ctypes.c_void_p
        lib.rta_base.argtypes = [ctypes.c_void_p]
        lib.rta_detach.restype = None
        lib.rta_detach.argtypes = [ctypes.c_void_p]
        lib.rta_unlink.restype = ctypes.c_int
        lib.rta_unlink.argtypes = [ctypes.c_char_p]
        _LIB = lib
        return _LIB


class Arena:
    """One host-wide shared-memory arena (plasma-equivalent segment).

    The head creates it; every local worker attaches. ``alloc`` returns a
    (payload_offset, generation) pair; readers ``pin`` with that pair before
    taking zero-copy views and ``unpin`` when done — a free racing with a
    reader defers until the last unpin (see arena.cc).
    """

    def __init__(self, lib: ctypes.CDLL, handle: int, name: str, created: bool):
        self._lib = lib
        self._h = handle
        self.name = name
        self.created = created
        base = lib.rta_base(handle)
        seg = lib.rta_segment_size(handle)
        # One process-lifetime view over the whole mapping; slices of it are
        # handed to pickle as out-of-band buffers (zero copy). Payload
        # offsets from the C API are relative to the segment base.
        self._mv = memoryview((ctypes.c_ubyte * seg).from_address(base)).cast("B")

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, name: str, size: int) -> Optional["Arena"]:
        lib = load()
        if lib is None:
            return None
        h = lib.rta_create(name.encode(), size)
        if not h:
            return None
        return cls(lib, h, name, created=True)

    @classmethod
    def attach(cls, name: str) -> Optional["Arena"]:
        lib = load()
        if lib is None:
            return None
        h = lib.rta_attach(name.encode())
        if not h:
            return None
        return cls(lib, h, name, created=False)

    def unlink(self) -> None:
        self._lib.rta_unlink(self.name.encode())

    # -- allocation --------------------------------------------------------

    def alloc(self, size: int) -> Optional[tuple[int, int]]:
        gen = ctypes.c_uint64(0)
        off = self._lib.rta_alloc(self._h, size, ctypes.byref(gen))
        if off == 0:
            return None
        return off, gen.value

    def free(self, off: int, gen: int) -> int:
        return self._lib.rta_free(self._h, off, gen)

    def pin(self, off: int, gen: int) -> bool:
        return bool(self._lib.rta_pin(self._h, off, gen))

    def unpin(self, off: int) -> None:
        self._lib.rta_unpin(self._h, off)

    # -- views -------------------------------------------------------------

    def view(self, off: int, length: int) -> memoryview:
        """Zero-copy view of `length` payload bytes at `off`. Caller must
        hold a pin for as long as any derived view lives."""
        return self._mv[off : off + length]

    # -- stats -------------------------------------------------------------

    @property
    def used(self) -> int:
        return self._lib.rta_used(self._h)

    @property
    def capacity(self) -> int:
        return self._lib.rta_capacity(self._h)

    @property
    def n_objects(self) -> int:
        return self._lib.rta_n_objects(self._h)
