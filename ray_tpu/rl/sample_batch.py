"""SampleBatch: columnar rollout storage + GAE.

Reference: ``rllib/policy/sample_batch.py`` (dict of stacked arrays with
OBS/ACTIONS/REWARDS/... keys, concat/slice/shuffle) and
``rllib/evaluation/postprocessing.py`` (compute_advantages, GAE). Kept as
plain numpy on the host; learners device_put whole minibatches.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
LOGP = "logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
NEXT_OBS = "next_obs"


class SampleBatch(dict):
    """dict[str, np.ndarray] with aligned first dimension."""

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat(batches: list["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if b and b.count]
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({k: np.concatenate([b[k] for b in batches]) for k in keys})

    def shuffle(self, rng: Optional[np.random.Generator] = None) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int, rng=None) -> Iterator["SampleBatch"]:
        b = self.shuffle(rng)
        n = b.count
        for s in range(0, n - size + 1, size):
            yield SampleBatch({k: v[s : s + size] for k, v in b.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    terminateds: np.ndarray,
    truncateds: np.ndarray,
    last_values: np.ndarray,
    gamma: float = 0.99,
    lam: float = 0.95,
    truncation_values: Optional[np.ndarray] = None,
):
    """Generalized advantage estimation over (T, N) rollout arrays.

    Matches the reference's GAE (``postprocessing.py compute_advantages``):
    at a TERMINATED step the bootstrap value is 0; at a TRUNCATED step the
    trajectory is cut but bootstrapped with the critic's value of the TRUE
    next state — pass ``truncation_values`` (T, N), the critic's value of
    each step's pre-reset final obs, to supply it (EnvRunner.sample does);
    without it the stored value of the reset obs is the fallback
    approximation.
    Returns (advantages, value_targets), both (T, N) float32.
    """
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_values = np.concatenate([values[1:], last_values[None]], axis=0)
    for t in range(T - 1, -1, -1):
        nv = next_values[t]
        if truncation_values is not None:
            nv = np.where(truncateds[t], truncation_values[t], nv)
        nv = np.where(terminateds[t], 0.0, nv)
        delta = rewards[t] + gamma * nv - values[t]
        # Cut the GAE recursion at ANY episode boundary (term or trunc).
        boundary = terminateds[t] | truncateds[t]
        last_gae = delta + gamma * lam * np.where(boundary, 0.0, last_gae)
        adv[t] = last_gae
    targets = adv + values
    return adv.astype(np.float32), targets.astype(np.float32)
