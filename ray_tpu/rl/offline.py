"""Offline RL data: recorded experience in, SampleBatches out.

Counterpart of the reference's offline stack (``rllib/offline/`` —
JsonReader/JsonWriter experience files, ``input_``/``output`` config keys,
DatasetReader over ray.data). TPU-first simplification: transitions are
columnar numpy arrays (obs/actions/rewards/next_obs/terminateds) stored as
one ``.npz`` per shard — the mmap-friendly, device-batchable layout — with a
JSONL import path for interoperability.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterable, Optional

import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.sample_batch import SampleBatch

_COLUMNS = (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS, sb.TERMINATEDS)


class OfflineDataset:
    """An in-memory columnar transition store with uniform sampling."""

    def __init__(self, columns: dict, seed: Optional[int] = None):
        self.columns = {k: np.asarray(v) for k, v in columns.items()}
        n = len(self.columns[sb.OBS])
        for k, v in self.columns.items():
            assert len(v) == n, f"column {k} length {len(v)} != {n}"
        self.count = n
        self._rng = np.random.default_rng(seed)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_batches(cls, batches: Iterable[SampleBatch], seed=None) -> "OfflineDataset":
        batches = list(batches)
        cols = {
            k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in batches[0]
            if k in _COLUMNS
        }
        return cls(cols, seed=seed)

    @classmethod
    def from_npz(cls, path_or_glob: str, seed=None) -> "OfflineDataset":
        paths = sorted(glob.glob(path_or_glob)) or [path_or_glob]
        parts = [np.load(p) for p in paths]
        cols = {
            k: np.concatenate([p[k] for p in parts]) for k in parts[0].files
        }
        return cls(cols, seed=seed)

    @classmethod
    def resolve(cls, data, seed=None) -> "OfflineDataset":
        """Accept a dataset, an .npz path/glob, or a .jsonl path (the
        algorithms' ``offline_data`` config key)."""
        if isinstance(data, cls):
            return data
        if isinstance(data, str):
            if data.endswith((".jsonl", ".json")):
                return cls.from_jsonl(data, seed=seed)
            return cls.from_npz(data, seed=seed)
        raise ValueError(
            "offline_data is required: pass an OfflineDataset or a path to "
            f".npz/.jsonl experience (got {data!r})"
        )

    @classmethod
    def from_jsonl(cls, path: str, seed=None) -> "OfflineDataset":
        """One JSON object per line with transition fields (reference:
        JsonReader's episode rows)."""
        cols: dict[str, list] = {k: [] for k in _COLUMNS}
        with open(path) as f:
            for line in f:
                row = json.loads(line)
                for k in _COLUMNS:
                    if k in row:
                        cols[k].append(row[k])
        return cls({k: v for k, v in cols.items() if v}, seed=seed)

    # -- io ------------------------------------------------------------------

    def save_npz(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        np.savez_compressed(path, **self.columns)
        return path

    # -- access --------------------------------------------------------------

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self.count, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self.columns.items()})

    def __len__(self) -> int:
        return self.count


def record_experience(
    env_name: str,
    n_steps: int,
    policy=None,
    seed: int = 0,
) -> OfflineDataset:
    """Roll a (scripted or random) policy in ``env_name`` and return the
    transitions — the reference's ``output`` experience-writing config, as a
    function. ``policy(obs) -> action`` defaults to uniform-random."""
    from ray_tpu.rl.env import SyncVectorEnv, make_env

    env = make_env(env_name)
    rng = np.random.default_rng(seed)
    cols: dict[str, list] = {k: [] for k in _COLUMNS}
    obs, _ = env.reset(seed=seed)
    for _ in range(n_steps):
        if policy is None:
            act = env.action_space.sample(rng)
        else:
            act = policy(obs)
        nxt, rew, term, trunc, _ = env.step(act)
        # raw appends only — the float32 conversion happens ONCE on the
        # whole column below, not per step inside the rollout loop
        cols[sb.OBS].append(obs)
        cols[sb.ACTIONS].append(act)
        cols[sb.REWARDS].append(rew)
        cols[sb.NEXT_OBS].append(nxt)  # envs return fresh arrays per step
        cols[sb.TERMINATEDS].append(bool(term))
        if term or trunc:
            obs, _ = env.reset()
        else:
            obs = nxt
    return OfflineDataset({
        k: np.asarray(v, np.float32) if k in (sb.OBS, sb.NEXT_OBS, sb.REWARDS)
        else np.asarray(v)
        for k, v in cols.items()
    })
