"""Replay buffers for off-policy algorithms.

Reference: ``rllib/utils/replay_buffers/`` (ReplayBuffer,
PrioritizedEpisodeReplayBuffer). Columnar numpy ring buffers: sampling
returns a SampleBatch ready for one device_put.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO ring buffer over columnar storage."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._store: dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not n:
            return
        if not self._store:
            for k, v in batch.items():
                self._store[k] = np.zeros((self.capacity,) + v.shape[1:], v.dtype)
        for k, v in batch.items():
            idx = (self._idx + np.arange(n)) % self.capacity
            self._store[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._store.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (sum-tree-free O(n) variant — fine at
    the ≤1e6 sizes the learning tests use; reference uses a segment tree)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6, beta: float = 0.4, seed=None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        idx = (self._idx + np.arange(n)) % self.capacity
        super().add(batch)
        self._prio[idx] = self._max_prio

    def sample(self, batch_size: int) -> SampleBatch:
        p = self._prio[: self._size] ** self.alpha
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights /= weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._store.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray, prios: np.ndarray) -> None:
        prios = np.abs(prios) + 1e-6
        self._prio[idx] = prios
        self._max_prio = max(self._max_prio, float(prios.max()))
