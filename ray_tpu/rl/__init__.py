"""ray_tpu.rl: reinforcement learning on the task/actor runtime.

Reference: ``rllib/`` — Algorithm/AlgorithmConfig driver, EnvRunner sampling
actors, Learner/LearnerGroup updates, replay buffers, spaces, env registry.
Compute is jax end-to-end: policies jit on CPU inside env runners; learner
updates pjit over the local device mesh (DP axis ≈ the reference's DDP).
"""

from ray_tpu.rl.algorithm import (  # noqa: F401
    Algorithm,
    AlgorithmConfig,
    get_algorithm_class,
    register_algorithm,
)
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rl.algorithms.es import ES, ESConfig  # noqa: F401
from ray_tpu.rl.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rl.multi_agent import (  # noqa: F401
    MultiAgentEnv,
    MultiAgentVectorEnv,
)
from ray_tpu.rl.env import (  # noqa: F401
    CartPoleEnv,
    Env,
    GridWorldEnv,
    PendulumEnv,
    SyncVectorEnv,
    make_env,
    register_env,
)
from ray_tpu.rl.env_runner import EnvRunner  # noqa: F401
from ray_tpu.rl.learner import Learner, LearnerGroup  # noqa: F401
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer  # noqa: F401
from ray_tpu.rl.rl_module import ActorCriticModule, QModule, RLModuleSpec  # noqa: F401
from ray_tpu.rl.sample_batch import SampleBatch, compute_gae  # noqa: F401
from ray_tpu.rl import spaces  # noqa: F401
