"""APPO — asynchronous PPO.

Reference: ``rllib/algorithms/appo/appo.py`` — IMPALA's async architecture
(decoupled runner futures, V-trace off-policy correction, per-runner weight
broadcast) with PPO's clipped-surrogate policy objective instead of the
plain importance-weighted policy gradient, plus an optional KL penalty
toward the behavior policy. Inherits everything from this repo's IMPALA —
only the jitted loss differs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import register_algorithm
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig, vtrace
from ray_tpu.rl.rl_module import ActorCriticModule


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.3        # PPO surrogate clip
        self.use_kl_loss = False     # optional KL(behavior || target) penalty
        self.kl_coeff = 0.2

    algo_class = None  # set below


def appo_loss(gamma: float, rho_bar: float, c_bar: float, vf_coeff: float,
              ent_coeff: float, clip_param: float, use_kl: bool, kl_coeff: float):
    def loss_fn(module: ActorCriticModule, params, batch):
        logp, entropy, values = module.logp_entropy_value(
            params, batch[sb.OBS], batch[sb.ACTIONS]
        )
        vs, pg_adv = vtrace(
            batch[sb.LOGP], jax.lax.stop_gradient(logp),
            batch[sb.REWARDS], batch[sb.TERMINATEDS],
            jax.lax.stop_gradient(values), batch["bootstrap_value"],
            gamma, rho_bar, c_bar,
        )
        # normalize advantages like synchronous PPO
        pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

        ratio = jnp.exp(logp - batch[sb.LOGP])
        surr = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - clip_param, 1.0 + clip_param) * pg_adv,
        )
        pi_loss = -jnp.mean(surr)
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        ent = jnp.mean(entropy)
        total = pi_loss + vf_coeff * vf_loss - ent_coeff * ent
        metrics = {"policy_loss": pi_loss, "vf_loss": vf_loss, "entropy": ent}
        if use_kl:
            # k3 estimator of KL(behavior || target) from behavior samples:
            # r = target/behavior, E_b[r - 1 - log r] = KL(b||t), >= 0
            logr = logp - batch[sb.LOGP]
            kl = jnp.mean(jnp.exp(logr) - 1.0 - logr)
            total = total + kl_coeff * kl
            metrics["kl"] = kl
        return total, metrics

    return loss_fn


class APPO(IMPALA):
    @classmethod
    def get_default_config(cls) -> "APPOConfig":
        return APPOConfig()

    def _make_loss(self, cfg):
        return appo_loss(
            cfg.gamma, cfg.vtrace_clip_rho_threshold, cfg.vtrace_clip_c_threshold,
            cfg.vf_loss_coeff, cfg.entropy_coeff, cfg.clip_param,
            cfg.use_kl_loss, cfg.kl_coeff,
        )


APPOConfig.algo_class = APPO
register_algorithm("APPO", APPO)
