"""Dreamer — model-based RL: a learned world model trained from replayed
experience, with the actor-critic trained ON IMAGINED rollouts in latent
space, never on raw environment returns.

Reference capability: ``rllib/algorithms/dreamerv3`` (world model + actor +
critic, imagination training). TPU-first redesign rather than a port —
documented departures from the full DreamerV3:

* latents are deterministic Markov features ``z = enc(obs)`` (no RSSM
  recurrence / categorical posteriors): the MinAtar/classic-control envs
  this build's learning tests run are near-Markov, and a feedforward
  latent keeps every train path a single fused XLA program;
* the world model is grounded by observation reconstruction + reward +
  continue heads (the Dreamer losses), with dynamics ``g(z, a) -> z'``
  trained against the online encoder's stop-gradiented target;
* imagination: H-step rollouts under the current policy inside the latent
  space — TD(lambda) returns with an EMA target critic, REINFORCE-with-
  baseline actor gradient + entropy bonus, and DreamerV3's return
  normalization (scale by a percentile range, never amplify small
  returns).

Everything jits once: world-model update, imagination, actor/critic
updates are three fused programs over static shapes.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.rl_module import _mlp_apply, _mlp_init
from ray_tpu.rl.sample_batch import SampleBatch
from ray_tpu.rl.spaces import Discrete


class DreamerConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4                 # world model
        self.actor_lr = 1e-4
        self.critic_lr = 3e-4
        self.latent_dim = 128
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.sample_steps_per_iter = 400
        self.updates_per_iter = 16
        self.train_batch_size = 128
        self.imagination_horizon = 8
        self.gae_lambda = 0.95
        self.entropy_coeff = 3e-3
        self.critic_ema = 0.02         # target critic update rate
        self.return_percentile = 95.0  # DreamerV3 return-normalization range

    algo_class = None  # set below


class DreamerModule:
    """Sampling-side module (EnvRunner protocol: init / sample_action).
    The policy acts on the encoder's latent — the SAME weights imagination
    trains against, so behavior and imagination stay consistent."""

    def __init__(self, spec):
        self.spec = spec
        if not isinstance(spec.action_space, Discrete):
            raise ValueError("Dreamer (this build) supports discrete actions")
        self.discrete = True  # EnvRunner protocol
        self.act_dim = spec.action_space.n
        self.obs_dim = int(np.prod(spec.observation_space.shape))
        self.latent = int(getattr(spec, "latent_dim", 128) or 128)
        self.hidden = list(spec.hidden)

    def init(self, rng: jax.Array) -> dict:
        k = jax.random.split(rng, 7)
        z, h, a, o = self.latent, self.hidden, self.act_dim, self.obs_dim
        return {
            "enc": _mlp_init(k[0], [o] + h + [z], final_scale=1.0),
            "dyn": _mlp_init(k[1], [z + a] + h + [z], final_scale=1.0),
            "rew": _mlp_init(k[2], [z + a] + h + [1], final_scale=1.0),
            "cont": _mlp_init(k[3], [z + a] + h + [1], final_scale=1.0),
            "dec": _mlp_init(k[4], [z] + h + [o], final_scale=1.0),
            "pi": _mlp_init(k[5], [z] + h + [a]),
            "v": _mlp_init(k[6], [z] + h + [1], final_scale=1.0),
        }

    # -- world model pieces ------------------------------------------------
    def encode(self, params, obs):
        flat = obs.reshape(obs.shape[0], -1)
        return jnp.tanh(_mlp_apply(params["enc"], flat))

    def _za(self, z, a):
        onehot = jax.nn.one_hot(a.astype(jnp.int32), self.act_dim, dtype=z.dtype)
        return jnp.concatenate([z, onehot], axis=-1)

    def dynamics(self, params, z, a):
        return jnp.tanh(_mlp_apply(params["dyn"], self._za(z, a)))

    def reward(self, params, z, a):
        return _mlp_apply(params["rew"], self._za(z, a))[..., 0]

    def cont_logit(self, params, z, a):
        return _mlp_apply(params["cont"], self._za(z, a))[..., 0]

    def decode(self, params, z):
        return _mlp_apply(params["dec"], z)

    # -- policy / value ----------------------------------------------------
    def pi_logits(self, params, z):
        return _mlp_apply(params["pi"], z)

    def value(self, params, z, key="v"):
        return _mlp_apply(params[key], z)[..., 0]

    # -- EnvRunner protocol ------------------------------------------------
    def apply(self, params: dict, obs: jax.Array) -> dict:
        z = self.encode(params, obs)
        return {"logits": self.pi_logits(params, z), "value": self.value(params, z)}

    def sample_action(self, params: dict, obs: jax.Array, rng: jax.Array):
        out = self.apply(params, obs)
        action = jax.random.categorical(rng, out["logits"], axis=-1)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(out["logits"], axis=-1), action[:, None], axis=-1
        )[:, 0]
        return action, logp, out["value"]


class Dreamer(Algorithm):
    def _module_cls(self):
        return DreamerModule

    def _setup(self):
        import optax

        cfg = self.config
        runner = self._local_runner
        spec = runner.spec if runner is not None else None
        if spec is None:  # remote runners: rebuild the spec locally
            from ray_tpu.rl.env import make_env
            from ray_tpu.rl.rl_module import RLModuleSpec

            env = make_env(cfg.env)
            spec = RLModuleSpec(env.observation_space, env.action_space, hidden=tuple(cfg.hidden))
        spec.latent_dim = cfg.latent_dim
        self.module = DreamerModule(spec)
        self.params = self.module.init(jax.random.PRNGKey(cfg.seed or 0))
        self.params["v_target"] = jax.tree.map(lambda x: x, self.params["v"])
        self.buffer = ReplayBuffer(cfg.buffer_size)
        self._rng = jax.random.PRNGKey((cfg.seed or 0) + 1)

        wm_keys = ("enc", "dyn", "rew", "cont", "dec")
        self._wm_opt = optax.adam(cfg.lr)
        self._pi_opt = optax.adam(cfg.actor_lr)
        self._v_opt = optax.adam(cfg.critic_lr)
        self._wm_state = self._wm_opt.init({k: self.params[k] for k in wm_keys})
        self._pi_state = self._pi_opt.init(self.params["pi"])
        self._v_state = self._v_opt.init(self.params["v"])
        mod, H = self.module, cfg.imagination_horizon

        def wm_loss(wm, batch):
            z = mod.encode(wm, batch[sb.OBS])  # enc lives in wm
            z_next = mod.encode(wm, batch[sb.NEXT_OBS])
            pred_next = mod.dynamics(wm, z, batch[sb.ACTIONS])
            pred_r = mod.reward(wm, z, batch[sb.ACTIONS])
            pred_c = mod.cont_logit(wm, z, batch[sb.ACTIONS])
            recon = mod.decode(wm, z)
            flat = batch[sb.OBS].reshape(z.shape[0], -1)
            done = batch[sb.TERMINATEDS].astype(jnp.float32)
            l_dyn = jnp.mean((pred_next - jax.lax.stop_gradient(z_next)) ** 2)
            l_rew = jnp.mean((pred_r - batch[sb.REWARDS]) ** 2)
            l_cont = jnp.mean(
                optax.sigmoid_binary_cross_entropy(pred_c, 1.0 - done)
            )
            l_rec = jnp.mean((recon - flat) ** 2)
            return l_dyn + l_rew + l_cont + 0.1 * l_rec, {
                "dyn": l_dyn, "rew": l_rew, "cont": l_cont, "recon": l_rec
            }

        def wm_update(params, wm_state, batch):
            wm = {k: params[k] for k in wm_keys}
            (loss, parts), grads = jax.value_and_grad(wm_loss, has_aux=True)(wm, batch)
            updates, wm_state = self._wm_opt.update(grads, wm_state)
            wm = optax.apply_updates(wm, updates)
            return {**params, **wm}, wm_state, loss, parts

        def imagine(params, z0, rng):
            """Roll H steps under pi inside the model. Returns per-step
            (z, a, logp, entropy, r, cont) stacked [H, B, ...]."""

            def step(carry, key):
                z = carry
                logits = mod.pi_logits(params, z)
                a = jax.random.categorical(key, logits, axis=-1)
                logsm = jax.nn.log_softmax(logits, axis=-1)
                logp = jnp.take_along_axis(logsm, a[:, None], axis=-1)[:, 0]
                ent = -jnp.sum(jnp.exp(logsm) * logsm, axis=-1)
                r = mod.reward(params, z, a)
                cont = jax.nn.sigmoid(mod.cont_logit(params, z, a))
                z_next = mod.dynamics(params, z, a)
                return z_next, (z, a, logp, ent, r, cont)

            keys = jax.random.split(rng, H)
            z_last, traj = jax.lax.scan(step, z0, keys)
            return z_last, traj

        def lambda_returns(params, traj, z_last):
            zs, _a, _lp, _ent, rs, conts = traj
            gamma, lam = cfg.gamma, cfg.gae_lambda
            v_last = mod.value(params, z_last, "v_target")

            def back(acc, inputs):
                r, cont, v_next = inputs
                ret = r + gamma * cont * ((1 - lam) * v_next + lam * acc)
                return ret, ret

            vs_next = jnp.concatenate(
                [mod.value(params, zs[1:].reshape(-1, zs.shape[-1]), "v_target").reshape(
                    H - 1, -1
                ), v_last[None]],
                axis=0,
            )
            _, rets = jax.lax.scan(
                back, v_last, (rs, conts, vs_next), reverse=True
            )
            return rets  # [H, B]

        def ac_update(params, pi_state, v_state, batch, rng):
            z0 = jax.lax.stop_gradient(mod.encode(params, batch[sb.OBS]))
            z_last, traj = imagine(params, z0, rng)
            zs, acts, logps, ents, rs, conts = jax.tree.map(
                jax.lax.stop_gradient, traj
            )
            rets = jax.lax.stop_gradient(lambda_returns(params, traj, z_last))
            # DreamerV3 return normalization: divide by the percentile range
            # of returns, floored at 1 (never AMPLIFY small returns)
            lo = jnp.percentile(rets, 100 - cfg.return_percentile)
            hi = jnp.percentile(rets, cfg.return_percentile)
            scale = jnp.maximum(hi - lo, 1.0)

            def critic_loss(v_params):
                v = mod.value({**params, "v": v_params}, zs.reshape(-1, zs.shape[-1]))
                return jnp.mean((v - rets.reshape(-1)) ** 2)

            vl, v_grads = jax.value_and_grad(critic_loss)(params["v"])
            v_updates, v_state = self._v_opt.update(v_grads, v_state)
            v_new = optax.apply_updates(params["v"], v_updates)

            def actor_loss(pi_params):
                p = {**params, "pi": pi_params}
                logits = mod.pi_logits(p, zs.reshape(-1, zs.shape[-1]))
                logsm = jax.nn.log_softmax(logits, axis=-1)
                logp = jnp.take_along_axis(
                    logsm, acts.reshape(-1)[:, None], axis=-1
                )[:, 0]
                ent = -jnp.sum(jnp.exp(logsm) * logsm, axis=-1)
                base = mod.value(params, zs.reshape(-1, zs.shape[-1]), "v_target")
                adv = (rets.reshape(-1) - base) / scale
                return -jnp.mean(logp * adv + cfg.entropy_coeff * ent)

            al, pi_grads = jax.value_and_grad(actor_loss)(params["pi"])
            pi_updates, pi_state = self._pi_opt.update(pi_grads, pi_state)
            pi_new = optax.apply_updates(params["pi"], pi_updates)
            # EMA target critic
            tau = cfg.critic_ema
            v_tgt = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, params["v_target"], v_new
            )
            out = {**params, "pi": pi_new, "v": v_new, "v_target": v_tgt}
            return out, pi_state, v_state, al, vl, jnp.mean(rets)

        self._wm_update = jax.jit(wm_update)
        self._ac_update = jax.jit(ac_update)

    def training_step(self) -> dict:
        cfg = self.config
        self._sync_weights()
        batches = self.foreach_runner("sample_transitions", cfg.sample_steps_per_iter)
        for b in batches:
            self.buffer.add(b)
            self._timesteps_total += b.count
        metrics = {}
        if len(self.buffer) < cfg.learning_starts:
            return {"status": "warmup", "buffer": len(self.buffer)}
        for _ in range(cfg.updates_per_iter):
            batch = self.buffer.sample(cfg.train_batch_size)
            jb = {
                k: jnp.asarray(v)
                for k, v in batch.items()
                if k in (sb.OBS, sb.NEXT_OBS, sb.ACTIONS, sb.REWARDS, sb.TERMINATEDS)
            }
            self.params, self._wm_state, wl, parts = self._wm_update(
                self.params, self._wm_state, jb
            )
            self._rng, key = jax.random.split(self._rng)
            (
                self.params,
                self._pi_state,
                self._v_state,
                al,
                vl,
                ret,
            ) = self._ac_update(self.params, self._pi_state, self._v_state, jb, key)
        metrics.update(
            world_model_loss=float(wl),
            actor_loss=float(al),
            critic_loss=float(vl),
            imagined_return_mean=float(ret),
            dyn_loss=float(parts["dyn"]),
            recon_loss=float(parts["recon"]),
        )
        return metrics

    def _sync_weights(self):
        # runners sample with enc+pi (+v for logging): ship the full tree
        if self._local_runner is not None:
            self._local_runner.set_weights(self.params)
        else:
            import ray_tpu

            ray_tpu.get(
                [a.set_weights.remote(self.params) for a in self._runner_actors]
            )


DreamerConfig.algo_class = Dreamer
register_algorithm("Dreamer", Dreamer)
