"""SAC — soft actor-critic for continuous control.

Reference: ``rllib/algorithms/sac/sac.py`` (off-policy replay + twin-Q +
squashed-gaussian policy + learned entropy temperature). TPU-first shape:
policy, twin Q, target Q and log-alpha live in ONE parameter pytree updated
by ONE jitted step — the three SAC objectives compose into a single loss
with stop-gradients where the textbook uses separate optimizers, so the
Learner's machinery (single pjit'd adam step, data-axis sharding) is reused
unchanged. Target networks update by Polyak averaging after each step.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.rl_module import _mlp_apply, _mlp_init
from ray_tpu.rl.sample_batch import SampleBatch
from ray_tpu.rl.spaces import Box

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.buffer_size = 100_000
        self.learning_starts = 1500
        self.sample_steps_per_iter = 400
        self.updates_per_iter = 200
        self.train_batch_size = 256
        self.tau = 0.005                  # polyak target update rate
        self.initial_alpha = 0.1
        self.target_entropy = "auto"      # -act_dim

    algo_class = None  # set below


class SACModule:
    """Squashed-gaussian policy + twin Q (+targets) + log_alpha."""

    discrete = False

    def __init__(self, spec):
        assert isinstance(spec.action_space, Box), "SAC needs a Box action space"
        self.spec = spec
        self.obs_dim = int(np.prod(spec.observation_space.shape))
        self.act_dim = int(np.prod(spec.action_space.shape))
        self.act_low = np.asarray(spec.action_space.low, np.float32).reshape(-1)
        self.act_high = np.asarray(spec.action_space.high, np.float32).reshape(-1)

    def init(self, rng):
        kp, k1, k2 = jax.random.split(rng, 3)
        h = list(self.spec.hidden)
        q_sizes = [self.obs_dim + self.act_dim] + h + [1]
        q1 = _mlp_init(k1, q_sizes, final_scale=1.0)
        q2 = _mlp_init(k2, q_sizes, final_scale=1.0)
        return {
            "pi": _mlp_init(kp, [self.obs_dim] + h + [2 * self.act_dim]),
            "q1": q1,
            "q2": q2,
            "target_q1": jax.tree_util.tree_map(jnp.copy, q1),
            "target_q2": jax.tree_util.tree_map(jnp.copy, q2),
            "log_alpha": jnp.asarray(np.log(0.1), jnp.float32),
        }

    # -- distributions -----------------------------------------------------

    def _pi(self, params, obs):
        out = _mlp_apply(params["pi"], obs, activation=jax.nn.relu)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
        return mean, log_std

    def _squash(self, u):
        scale = (self.act_high - self.act_low) / 2.0
        center = (self.act_high + self.act_low) / 2.0
        return jnp.tanh(u) * scale + center

    def sample_action_logp(self, params, obs, rng):
        mean, log_std = self._pi(params, obs)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(rng, mean.shape)
        # log-prob with tanh change of variables
        logp_u = jnp.sum(
            -0.5 * (((u - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1
        )
        logp = logp_u - jnp.sum(2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)
        return self._squash(u), logp

    def sample_action(self, params, obs, rng):
        """EnvRunner interface: (action, logp, value-placeholder)."""
        a, logp = self.sample_action_logp(params, obs, rng)
        return a, logp, jnp.zeros(a.shape[:-1], jnp.float32)

    def q_values(self, params, obs, act, target=False):
        x = jnp.concatenate([obs, act], axis=-1)
        k1, k2 = ("target_q1", "target_q2") if target else ("q1", "q2")
        q1 = _mlp_apply(params[k1], x, activation=jax.nn.relu)[..., 0]
        q2 = _mlp_apply(params[k2], x, activation=jax.nn.relu)[..., 0]
        return q1, q2


def sac_loss(gamma: float, target_entropy: float):
    def loss_fn(module: SACModule, params, batch):
        obs, act = batch[sb.OBS], batch[sb.ACTIONS]
        next_obs = batch[sb.NEXT_OBS]
        rew = batch[sb.REWARDS]
        done = batch[sb.TERMINATEDS].astype(jnp.float32)
        rng = jax.random.fold_in(jax.random.PRNGKey(0), batch["step"][0])
        alpha = jnp.exp(params["log_alpha"])

        # -- critic target (no gradients) ---------------------------------
        next_a, next_logp = module.sample_action_logp(
            jax.lax.stop_gradient(params), next_obs, jax.random.fold_in(rng, 1)
        )
        tq1, tq2 = module.q_values(params, next_obs, next_a, target=True)
        target_v = jnp.minimum(tq1, tq2) - jax.lax.stop_gradient(alpha) * next_logp
        target = jax.lax.stop_gradient(rew + gamma * (1.0 - done) * target_v)
        q1, q2 = module.q_values(params, obs, act)
        q_loss = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)

        # -- actor (Q params frozen) --------------------------------------
        pi_a, pi_logp = module.sample_action_logp(params, obs, jax.random.fold_in(rng, 2))
        fq1, fq2 = module.q_values(jax.lax.stop_gradient(params), obs, pi_a)
        pi_loss = jnp.mean(jax.lax.stop_gradient(alpha) * pi_logp - jnp.minimum(fq1, fq2))

        # -- temperature ---------------------------------------------------
        alpha_loss = -jnp.mean(
            params["log_alpha"] * jax.lax.stop_gradient(pi_logp + target_entropy)
        )

        total = q_loss + pi_loss + alpha_loss
        return total, {
            "q_loss": q_loss,
            "pi_loss": pi_loss,
            "alpha": alpha,
            "entropy": -jnp.mean(pi_logp),
        }

    return loss_fn


def _polyak(tau: float):
    def update(learner):
        p = dict(learner.params)
        for src, dst in (("q1", "target_q1"), ("q2", "target_q2")):
            p[dst] = jax.tree_util.tree_map(
                lambda t, s: (1.0 - tau) * t + tau * s, p[dst], p[src]
            )
        learner.params = p
        return True

    return update


class SAC(Algorithm):
    @classmethod
    def get_default_config(cls) -> "SACConfig":
        return SACConfig()

    def _module_cls(self):
        return SACModule

    def _setup(self):
        cfg: SACConfig = self.config
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        from ray_tpu.rl.rl_module import RLModuleSpec

        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        tgt_ent = (
            -float(np.prod(act_space.shape))
            if cfg.target_entropy == "auto"
            else float(cfg.target_entropy)
        )
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: SACModule(spec),
                loss_fn=sac_loss(cfg.gamma, tgt_ent),
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._update_step = 0
        self.sync_weights(self.learner_group.get_weights())

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    def training_step(self) -> dict:
        cfg: SACConfig = self.config
        n_runners = max(1, len(self._runner_actors) or 1)
        n_envs = max(1, cfg.num_envs_per_env_runner)
        vec_steps = max(1, cfg.sample_steps_per_iter // (n_runners * n_envs))
        for b in self.foreach_runner("sample_transitions", vec_steps):
            self.buffer.add(b)
            self._timesteps_total += b.count
        metrics: dict = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                self._update_step += 1
                batch["step"] = np.full(batch.count, self._update_step, np.int32)
                metrics = self.learner_group.update(batch)
                self.learner_group.apply(_polyak(cfg.tau))
            self.sync_weights(self.learner_group.get_weights())
        return {f"learner/{k}": v for k, v in metrics.items()} | {
            "buffer_size": len(self.buffer)
        }


SACConfig.algo_class = SAC
register_algorithm("SAC", SAC)
