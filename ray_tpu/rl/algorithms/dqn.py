"""DQN — double-DQN with (optionally prioritized) replay.

Reference: ``rllib/algorithms/dqn/dqn.py`` (training_step: sample →
replay-buffer add → N learner updates → periodic target sync → ε decay).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.rl_module import QModule, RLModuleSpec
from ray_tpu.rl.sample_batch import SampleBatch


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.buffer_size = 50_000
        self.prioritized_replay = False
        self.learning_starts = 1000
        self.target_update_freq = 500    # in sampled env steps
        self.sample_steps_per_iter = 512
        self.updates_per_iter = 32
        self.train_batch_size = 64
        self.double_q = True
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 10_000

    algo_class = None  # set below


def dqn_loss(gamma: float, double_q: bool):
    def loss_fn(module: QModule, params, batch):
        q_all = module.q_values(params, batch[sb.OBS])
        q = jnp.take_along_axis(q_all, batch[sb.ACTIONS][:, None].astype(jnp.int32), axis=-1)[:, 0]
        q_next_target = module.q_values(params, batch[sb.NEXT_OBS], target=True)
        if double_q:
            q_next_online = module.q_values(params, batch[sb.NEXT_OBS])
            best = jnp.argmax(q_next_online, axis=-1)
        else:
            best = jnp.argmax(q_next_target, axis=-1)
        q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=-1)[:, 0]
        q_next = jax_stop_gradient(q_next)
        target = batch[sb.REWARDS] + gamma * (1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)) * q_next
        td = q - target
        weights = batch.get("weights")
        per_sample = huber(td)
        loss = jnp.mean(per_sample * weights) if weights is not None else jnp.mean(per_sample)
        # Per-sample |td| flows back as an aux array so prioritized replay
        # can set PER-SAMPLE priorities (reference: dqn updates priorities
        # with each sample's TD error, not a batch statistic).
        return loss, {
            "td_error_mean": jnp.mean(jnp.abs(td)),
            "q_mean": jnp.mean(q),
            "td_abs": jnp.abs(td),
        }

    return loss_fn


def huber(x, delta: float = 1.0):
    absx = jnp.abs(x)
    return jnp.where(absx <= delta, 0.5 * x * x, delta * (absx - 0.5 * delta))


def jax_stop_gradient(x):
    import jax

    return jax.lax.stop_gradient(x)


def _sync_target(learner) -> bool:
    import jax

    learner.params = dict(learner.params)
    learner.params["target_q"] = jax.tree_util.tree_map(lambda x: x, learner.params["q"])
    return True


class DQN(Algorithm):
    @classmethod
    def get_default_config(cls) -> "DQNConfig":
        return DQNConfig()

    def _module_cls(self):
        return QModule

    def _setup(self):
        cfg: DQNConfig = self.config
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: QModule(spec),
                loss_fn=dqn_loss(cfg.gamma, cfg.double_q),
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )
        self.buffer = (
            PrioritizedReplayBuffer(cfg.buffer_size, seed=cfg.seed)
            if cfg.prioritized_replay
            else ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        )
        self._steps_since_target_sync = 0
        self.sync_weights(self.learner_group.get_weights())
        self._update_epsilon()

    def _update_epsilon(self):
        cfg: DQNConfig = self.config
        frac = min(1.0, self._timesteps_total / max(cfg.epsilon_decay_steps, 1))
        eps = cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)
        self.foreach_runner("set_epsilon", float(eps))
        self._epsilon = eps

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    def training_step(self) -> dict:
        cfg: DQNConfig = self.config
        # 1) sample transitions from all runners. sample_steps_per_iter counts
        # TOTAL env steps per iteration (across runners AND their vector
        # slots), so epsilon decay / replay-ratio tuning is independent of the
        # runner topology.
        n_runners = max(1, len(self._runner_actors) or 1)
        n_envs = max(1, self.config.num_envs_per_env_runner)
        vec_steps = max(1, cfg.sample_steps_per_iter // (n_runners * n_envs))
        outs = self.foreach_runner("sample_transitions", vec_steps)
        for b in outs:
            self.buffer.add(b)
            self._timesteps_total += b.count
            self._steps_since_target_sync += b.count
        metrics: dict = {}
        # 2) learn
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                metrics = self.learner_group.update(batch)
                td_abs = metrics.pop("td_abs", None)
                if cfg.prioritized_replay and "batch_indexes" in batch and td_abs is not None:
                    # td_abs is already host numpy: Learner.update does ONE
                    # device_get for the whole metrics pytree — re-wrapping
                    # it per update would be a redundant sync in this loop
                    self.buffer.update_priorities(batch["batch_indexes"], td_abs)
            # 3) periodic target network sync + weight broadcast
            if self._steps_since_target_sync >= cfg.target_update_freq:
                self.learner_group.apply(_sync_target)
                self._steps_since_target_sync = 0
            self.sync_weights(self.learner_group.get_weights())
        self._update_epsilon()
        return {f"learner/{k}": v for k, v in metrics.items()} | {
            "epsilon": self._epsilon,
            "buffer_size": len(self.buffer),
        }


DQNConfig.algo_class = DQN
register_algorithm("DQN", DQN)
