"""CQL — conservative Q-learning for offline continuous control.

Reference: ``rllib/algorithms/cql/`` (SAC objectives + a conservative
penalty that pushes down Q-values of out-of-distribution actions so the
offline policy cannot exploit extrapolation error). Reuses this repo's SAC
module/loss composition (``sac.py``): one pytree, one jitted step; the CQL
regularizer adds a logsumexp over sampled random + policy actions minus the
dataset Q, weighted by ``cql_alpha``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.algorithms.sac import SACModule, _polyak, sac_loss
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.offline import OfflineDataset
from ray_tpu.rl.rl_module import RLModuleSpec


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.train_batch_size = 256
        self.updates_per_iter = 200
        self.tau = 0.005
        self.target_entropy = "auto"
        self.cql_alpha = 1.0          # conservative penalty weight
        self.cql_n_actions = 4        # sampled actions per state for logsumexp
        self.offline_data = None      # OfflineDataset | .npz/.jsonl path
        self.evaluation_steps = 0

    algo_class = None  # set below


def cql_loss(gamma: float, target_entropy: float, cql_alpha: float, n_actions: int):
    base = sac_loss(gamma, target_entropy)

    def loss_fn(module: SACModule, params, batch):
        total, metrics = base(module, params, batch)
        obs = batch[sb.OBS]
        act = batch[sb.ACTIONS]
        B = obs.shape[0]
        rng = jax.random.fold_in(jax.random.PRNGKey(1), batch["step"][0])

        # OOD action set: uniform-random + current-policy samples per state
        low = jnp.asarray(module.act_low)
        high = jnp.asarray(module.act_high)
        rand_a = jax.random.uniform(
            jax.random.fold_in(rng, 0),
            (n_actions, B, module.act_dim),
            minval=low,
            maxval=high,
        )
        pol_a, _ = module.sample_action_logp(
            jax.lax.stop_gradient(params),
            jnp.broadcast_to(obs, (n_actions,) + obs.shape),
            jax.random.fold_in(rng, 1),
        )
        cand = jnp.concatenate([rand_a, pol_a], axis=0)        # (2n, B, act)
        obs_rep = jnp.broadcast_to(obs, (2 * n_actions,) + obs.shape)
        q1_ood, q2_ood = module.q_values(
            params, obs_rep.reshape(-1, obs.shape[-1]), cand.reshape(-1, module.act_dim)
        )
        q1_ood = q1_ood.reshape(2 * n_actions, B)
        q2_ood = q2_ood.reshape(2 * n_actions, B)
        q1_data, q2_data = module.q_values(params, obs, act)

        # logsumexp over candidate actions ≈ max Q on OOD actions
        gap1 = jnp.mean(jax.scipy.special.logsumexp(q1_ood, axis=0) - q1_data)
        gap2 = jnp.mean(jax.scipy.special.logsumexp(q2_ood, axis=0) - q2_data)
        penalty = cql_alpha * (gap1 + gap2)
        metrics = dict(metrics)
        metrics["cql_penalty"] = penalty
        return total + penalty, metrics

    return loss_fn


class CQL(Algorithm):
    @classmethod
    def get_default_config(cls) -> "CQLConfig":
        return CQLConfig()

    def _module_cls(self):
        return SACModule

    def _setup(self):
        cfg: CQLConfig = self.config
        self.dataset: OfflineDataset = OfflineDataset.resolve(
            cfg.offline_data, seed=cfg.seed
        )
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        tgt_ent = (
            -float(np.prod(act_space.shape))
            if cfg.target_entropy == "auto"
            else float(cfg.target_entropy)
        )
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: SACModule(spec),
                loss_fn=cql_loss(cfg.gamma, tgt_ent, cfg.cql_alpha, cfg.cql_n_actions),
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )
        self._update_step = 0

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    def training_step(self) -> dict:
        cfg: CQLConfig = self.config
        metrics: dict = {}
        for _ in range(cfg.updates_per_iter):
            batch = self.dataset.sample(cfg.train_batch_size)
            self._update_step += 1
            batch["step"] = np.full(batch.count, self._update_step, np.int32)
            metrics = self.learner_group.update(batch)
            self.learner_group.apply(_polyak(cfg.tau))
        out = {f"learner/{k}": v for k, v in metrics.items()}
        if cfg.evaluation_steps > 0:
            self.sync_weights(self.learner_group.get_weights())
            n_runners = max(1, len(self._runner_actors) or 1)
            per = max(1, cfg.evaluation_steps // n_runners)
            for b in self.foreach_runner("sample_transitions", per):
                self._timesteps_total += b.count
        else:
            self._timesteps_total += cfg.updates_per_iter * cfg.train_batch_size
        return out


CQLConfig.algo_class = CQL
register_algorithm("CQL", CQL)
