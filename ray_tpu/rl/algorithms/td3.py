"""TD3 — twin-delayed deep deterministic policy gradient.

Reference: ``rllib/algorithms/td3/`` (DDPG + twin Q + target policy
smoothing + delayed policy updates). Same single-pytree/single-jitted-step
shape as this repo's SAC: the critic and (gated) actor objectives compose
into one loss with stop-gradients, the policy delay is a ``step % d`` gate
inside the jitted step (no Python-side alternation), and target networks
Polyak-update after each step.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.rl.rl_module import _mlp_apply, _mlp_init
from ray_tpu.rl.sample_batch import SampleBatch
from ray_tpu.rl.spaces import Box


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size = 100_000
        self.learning_starts = 1500
        self.sample_steps_per_iter = 400
        self.updates_per_iter = 200
        self.train_batch_size = 256
        self.tau = 0.005
        self.exploration_noise = 0.1      # env-side action noise
        self.target_noise = 0.2           # target policy smoothing
        self.target_noise_clip = 0.5
        self.policy_delay = 2             # actor updates every d critic steps
        self.twin_q = True                # False = classic DDPG single critic

    algo_class = None  # set below


class TD3Module:
    """Deterministic tanh policy + twin Q, each with target copies."""

    discrete = False

    def __init__(self, spec, exploration_noise: float = 0.1, twin_q: bool = True):
        assert isinstance(spec.action_space, Box), "TD3 needs a Box action space"
        self.spec = spec
        self.obs_dim = int(np.prod(spec.observation_space.shape))
        self.act_dim = int(np.prod(spec.action_space.shape))
        self.act_low = np.asarray(spec.action_space.low, np.float32).reshape(-1)
        self.act_high = np.asarray(spec.action_space.high, np.float32).reshape(-1)
        self.exploration_noise = exploration_noise
        self.twin_q = twin_q  # False = classic DDPG's single critic

    def init(self, rng):
        kp, k1, k2 = jax.random.split(rng, 3)
        h = list(self.spec.hidden)
        q_sizes = [self.obs_dim + self.act_dim] + h + [1]
        pi = _mlp_init(kp, [self.obs_dim] + h + [self.act_dim])
        q1 = _mlp_init(k1, q_sizes, final_scale=1.0)
        copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)  # noqa: E731
        params = {
            "pi": pi,
            "q1": q1,
            "target_pi": copy(pi),
            "target_q1": copy(q1),
        }
        if self.twin_q:
            q2 = _mlp_init(k2, q_sizes, final_scale=1.0)
            params["q2"] = q2
            params["target_q2"] = copy(q2)
        return params

    def _squash(self, u):
        scale = (self.act_high - self.act_low) / 2.0
        center = (self.act_high + self.act_low) / 2.0
        return jnp.tanh(u) * scale + center

    def policy(self, params, obs, target: bool = False):
        key = "target_pi" if target else "pi"
        return self._squash(_mlp_apply(params[key], obs, activation=jax.nn.relu))

    def sample_action(self, params, obs, rng):
        """EnvRunner interface: deterministic action + exploration noise."""
        a = self.policy(params, obs)
        noise = self.exploration_noise * jax.random.normal(rng, a.shape)
        a = jnp.clip(a + noise, jnp.asarray(self.act_low), jnp.asarray(self.act_high))
        zeros = jnp.zeros(a.shape[:-1], jnp.float32)
        return a, zeros, zeros

    def q_values(self, params, obs, act, target: bool = False):
        x = jnp.concatenate([obs, act], axis=-1)
        k1, k2 = ("target_q1", "target_q2") if target else ("q1", "q2")
        q1 = _mlp_apply(params[k1], x, activation=jax.nn.relu)[..., 0]
        if not self.twin_q:
            return q1, q1  # single critic: min() and the twin loss collapse
        q2 = _mlp_apply(params[k2], x, activation=jax.nn.relu)[..., 0]
        return q1, q2


def td3_loss(gamma: float, target_noise: float, noise_clip: float, policy_delay: int):
    def loss_fn(module: TD3Module, params, batch):
        obs, act = batch[sb.OBS], batch[sb.ACTIONS]
        rew = batch[sb.REWARDS]
        done = batch[sb.TERMINATEDS].astype(jnp.float32)
        next_obs = batch[sb.NEXT_OBS]
        step = batch["step"][0]
        rng = jax.random.fold_in(jax.random.PRNGKey(0), step)

        # -- critic: clipped double-Q target with smoothed target action ---
        next_a = module.policy(jax.lax.stop_gradient(params), next_obs, target=True)
        smooth = jnp.clip(
            target_noise * jax.random.normal(rng, next_a.shape),
            -noise_clip,
            noise_clip,
        )
        next_a = jnp.clip(
            next_a + smooth, jnp.asarray(module.act_low), jnp.asarray(module.act_high)
        )
        tq1, tq2 = module.q_values(params, next_obs, next_a, target=True)
        target = jax.lax.stop_gradient(
            rew + gamma * (1.0 - done) * jnp.minimum(tq1, tq2)
        )
        q1, q2 = module.q_values(params, obs, act)
        q_loss = jnp.mean((q1 - target) ** 2)
        if module.twin_q:
            q_loss = q_loss + jnp.mean((q2 - target) ** 2)

        # -- actor, gated by the policy delay (Q frozen) -------------------
        pi_a = module.policy(params, obs)
        fq1, _ = module.q_values(jax.lax.stop_gradient(params), obs, pi_a)
        do_pi = (step % policy_delay == 0).astype(jnp.float32)
        pi_loss = -do_pi * jnp.mean(fq1)

        return q_loss + pi_loss, {
            "q_loss": q_loss,
            "pi_loss": pi_loss,
            "q_mean": jnp.mean(q1),
        }

    return loss_fn


def _polyak_all(tau: float):
    def update(learner):
        p = dict(learner.params)
        pairs = (("pi", "target_pi"), ("q1", "target_q1"), ("q2", "target_q2"))
        for src, dst in ((s, d) for s, d in pairs if d in p):
            p[dst] = jax.tree_util.tree_map(
                lambda t, s: (1.0 - tau) * t + tau * s, p[dst], p[src]
            )
        learner.params = p
        return True

    return update


class TD3(Algorithm):
    @classmethod
    def get_default_config(cls) -> "TD3Config":
        return TD3Config()

    def _module_cls(self):
        cfg = self.config

        def make(spec):
            return TD3Module(
                spec, exploration_noise=cfg.exploration_noise, twin_q=cfg.twin_q
            )

        return make

    def _setup(self):
        cfg: TD3Config = self.config
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        from ray_tpu.rl.rl_module import RLModuleSpec

        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: TD3Module(
                    spec, cfg.exploration_noise, twin_q=cfg.twin_q
                ),
                loss_fn=td3_loss(
                    cfg.gamma, cfg.target_noise, cfg.target_noise_clip, cfg.policy_delay
                ),
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._update_step = 0
        self.sync_weights(self.learner_group.get_weights())

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    def training_step(self) -> dict:
        cfg: TD3Config = self.config
        n_runners = max(1, len(self._runner_actors) or 1)
        n_envs = max(1, cfg.num_envs_per_env_runner)
        vec_steps = max(1, cfg.sample_steps_per_iter // (n_runners * n_envs))
        for b in self.foreach_runner("sample_transitions", vec_steps):
            self.buffer.add(b)
            self._timesteps_total += b.count
        metrics: dict = {}
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                batch = self.buffer.sample(cfg.train_batch_size)
                self._update_step += 1
                batch["step"] = np.full(batch.count, self._update_step, np.int32)
                metrics = self.learner_group.update(batch)
                self.learner_group.apply(_polyak_all(cfg.tau))
            self.sync_weights(self.learner_group.get_weights())
        return {f"learner/{k}": v for k, v in metrics.items()} | {
            "buffer_size": len(self.buffer)
        }


TD3Config.algo_class = TD3
register_algorithm("TD3", TD3)
