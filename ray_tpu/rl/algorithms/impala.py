"""IMPALA — async off-policy actor-critic with V-trace correction.

Reference: ``rllib/algorithms/impala/impala.py`` (async sample collection
from env-runner actors, V-trace-corrected learner updates, periodic weight
broadcast). TPU-first shape: runners stream time-major ``(N, T)`` sequence
batches as futures; the driver consumes whichever future lands first
(``ray_tpu.wait``), updates the learner (one jitted V-trace step — the scan
over T compiles to a single fused XLA loop), pushes fresh weights to that
runner only, and immediately resubmits its next rollout — sampling never
blocks on learning and vice versa.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.exceptions import RayActorError
from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec
from ray_tpu.rl.sample_batch import SampleBatch


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vtrace_clip_rho_threshold = 1.0
        self.vtrace_clip_c_threshold = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rollout_fragment_length = 50
        self.train_batch_size = 500     # env steps consumed per training_step
        self.broadcast_interval = 1     # updates between weight pushes to a runner

    algo_class = None  # set below


def vtrace(behavior_logp, target_logp, rewards, dones, values, bootstrap,
           gamma: float, rho_bar: float, c_bar: float):
    """V-trace targets + policy-gradient advantages over (N, T) sequences.

    Espeholt et al. 2018 eqs. (1)-(2); the backward recursion is a single
    ``lax.scan`` over T so the whole correction fuses into the update step.
    All inputs (N, T) except ``bootstrap`` (N,). Returns (vs, pg_adv), both
    (N, T) and gradient-stopped.
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(rho_bar, rhos)
    cs = jnp.minimum(c_bar, rhos)
    discounts = gamma * (1.0 - dones.astype(jnp.float32))
    next_values = jnp.concatenate([values[:, 1:], bootstrap[:, None]], axis=1)
    deltas = clipped_rhos * (rewards + discounts * next_values - values)

    def body(acc, xs):
        delta_t, disc_t, c_t = xs
        acc = delta_t + disc_t * c_t * acc
        return acc, acc

    # scan runs time-major back-to-front: transpose to (T, N) and flip.
    xs = (deltas.T[::-1], discounts.T[::-1], cs.T[::-1])
    _, out = jax.lax.scan(body, jnp.zeros_like(bootstrap), xs)
    vs = values + out[::-1].T
    vs_next = jnp.concatenate([vs[:, 1:], bootstrap[:, None]], axis=1)
    pg_adv = clipped_rhos * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def impala_loss(gamma: float, rho_bar: float, c_bar: float,
                vf_coeff: float, ent_coeff: float):
    def loss_fn(module: ActorCriticModule, params, batch):
        # (N, T, obs) / (N, T) sequence batch from sample_sequences.
        logp, entropy, values = module.logp_entropy_value(
            params, batch[sb.OBS], batch[sb.ACTIONS]
        )
        vs, pg_adv = vtrace(
            batch[sb.LOGP], jax.lax.stop_gradient(logp),
            batch[sb.REWARDS], batch[sb.TERMINATEDS],
            jax.lax.stop_gradient(values), batch["bootstrap_value"],
            gamma, rho_bar, c_bar,
        )
        pi_loss = -jnp.mean(logp * pg_adv)
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        ent = jnp.mean(entropy)
        total = pi_loss + vf_coeff * vf_loss - ent_coeff * ent
        return total, {"policy_loss": pi_loss, "vf_loss": vf_loss, "entropy": ent}

    return loss_fn


class IMPALA(Algorithm):
    @classmethod
    def get_default_config(cls) -> "IMPALAConfig":
        return IMPALAConfig()

    def _make_loss(self, cfg):
        """Loss builder — APPO subclasses swap in the clipped surrogate."""
        return impala_loss(
            cfg.gamma, cfg.vtrace_clip_rho_threshold,
            cfg.vtrace_clip_c_threshold, cfg.vf_loss_coeff,
            cfg.entropy_coeff,
        )

    def _setup(self):
        cfg: IMPALAConfig = self.config
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: ActorCriticModule(spec),
                loss_fn=self._make_loss(cfg),
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )
        self.sync_weights(self.learner_group.get_weights())
        # one in-flight (future, actor) per runner slot (async pipeline);
        # the actor is recorded so a future from a since-replaced actor is
        # never mistaken for a failure of the current one
        self._inflight: dict[int, tuple] = {}
        self._updates_since_broadcast: dict[int, int] = {}

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    # -- async sampling loop ------------------------------------------------

    def _submit(self, i: int):
        cfg: IMPALAConfig = self.config
        actor = self._runner_actors[i]
        self._inflight[i] = (
            actor.sample_sequences.remote(cfg.rollout_fragment_length, cfg.gamma),
            actor,
        )

    def restart_runner(self, index: int) -> None:
        super().restart_runner(index)
        self._inflight.pop(index, None)  # stale future from the dead actor

    def training_step(self) -> dict:
        cfg: IMPALAConfig = self.config
        metrics: dict = {}
        if self._local_runner is not None:
            # local mode: synchronous fallback, still V-trace-corrected
            steps = 0
            while steps < cfg.train_batch_size:
                batch = self._local_runner.sample_sequences(
                    cfg.rollout_fragment_length, cfg.gamma
                )
                steps += int(batch[sb.REWARDS].size)
                metrics = self.learner_group.update(batch)
                self._local_runner.set_weights(self.learner_group.get_weights())
            self._timesteps_total += steps
            return {f"learner/{k}": v for k, v in metrics.items()}

        for i in range(len(self._runner_actors)):
            if self._inflight.get(i) is None:
                self._submit(i)
        steps = 0
        while steps < cfg.train_batch_size:
            fut_to_idx = {f: i for i, (f, _) in self._inflight.items()}
            ready, _ = ray_tpu.wait(list(fut_to_idx), num_returns=1)
            i = fut_to_idx[ready[0]]
            try:
                batch: SampleBatch = ray_tpu.get(ready[0])
            except RayActorError:
                if not cfg.restart_failed_env_runners:
                    raise
                # only replace the runner if the failed future belongs to the
                # CURRENT actor — it may already have been restarted (e.g. by
                # a foreach_runner round between training_steps)
                if self._inflight[i][1] is self._runner_actors[i]:
                    self.restart_runner(i)
                else:
                    self._inflight.pop(i, None)
                self._submit(i)
                continue
            steps += int(batch[sb.REWARDS].size)
            metrics = self.learner_group.update(batch)
            # push fresh weights to the runner we just drained (stale-ness is
            # what V-trace corrects for; broadcast_interval throttles traffic)
            n = self._updates_since_broadcast.get(i, 0) + 1
            if n >= cfg.broadcast_interval:
                self._runner_actors[i].set_weights.remote(self.learner_group.get_weights())
                n = 0
            self._updates_since_broadcast[i] = n
            self._submit(i)
        self._timesteps_total += steps
        return {f"learner/{k}": v for k, v in metrics.items()} | {
            "num_env_steps_sampled": steps
        }


IMPALAConfig.algo_class = IMPALA
register_algorithm("IMPALA", IMPALA)
