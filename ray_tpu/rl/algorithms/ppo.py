"""PPO — clipped-surrogate policy optimization.

Reference: ``rllib/algorithms/ppo/ppo.py:405`` (training_step: sample via
WorkerSet → learner_group.update → sync_weights) and
``ppo_torch_learner`` loss. Here the loss is a pure jax function jitted once
inside the Learner; minibatch epochs run back-to-back device steps.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.learner import Learner, LearnerGroup
from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec
from ray_tpu.rl.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.lambda_ = 0.95
        self.train_batch_size = 4000
        self.minibatch_size = 128
        self.num_epochs = 8

    algo_class = None  # set below


def ppo_loss(clip_param: float, vf_clip: float, vf_coeff: float, ent_coeff: float):
    def loss_fn(module: ActorCriticModule, params, batch):
        logp, entropy, values = module.logp_entropy_value(
            params, batch[sb.OBS], batch[sb.ACTIONS]
        )
        adv = batch[sb.ADVANTAGES]
        ratio = jnp.exp(logp - batch[sb.LOGP])
        surr = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv
        )
        pi_loss = -jnp.mean(surr)
        vf_err = jnp.clip((values - batch[sb.VALUE_TARGETS]) ** 2, 0.0, vf_clip**2)
        vf_loss = jnp.mean(vf_err)
        ent = jnp.mean(entropy)
        total = pi_loss + vf_coeff * vf_loss - ent_coeff * ent
        kl = jnp.mean(batch[sb.LOGP] - logp)
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": ent,
            "kl": kl,
        }

    return loss_fn


class PPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> "PPOConfig":
        return PPOConfig()

    def _setup(self):
        cfg: PPOConfig = self.config
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: ActorCriticModule(spec),
                loss_fn=ppo_loss(
                    cfg.clip_param, cfg.vf_clip_param, cfg.vf_loss_coeff, cfg.entropy_coeff
                ),
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )
        self.sync_weights(self.learner_group.get_weights())
        self._mb_rng = np.random.default_rng(cfg.seed)

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    def training_step(self) -> dict:
        cfg: PPOConfig = self.config
        # 1) parallel sampling until train_batch_size steps are gathered
        batches: list[SampleBatch] = []
        gathered = 0
        while gathered < cfg.train_batch_size:
            out = self.foreach_runner("sample")
            batches.extend(out)
            gathered += sum(b.count for b in out)
        batch = SampleBatch.concat(batches)
        self._timesteps_total += batch.count
        # 2) advantage normalization (reference: standardize_fields=["advantages"])
        adv = batch[sb.ADVANTAGES]
        batch[sb.ADVANTAGES] = (adv - adv.mean()) / max(adv.std(), 1e-6)
        # 3) minibatch SGD epochs
        metrics: dict = {}
        mb = min(cfg.minibatch_size, batch.count)
        for _ in range(cfg.num_epochs):
            for minibatch in batch.minibatches(mb, self._mb_rng):
                metrics = self.learner_group.update(minibatch)
        # 4) broadcast new weights to runners
        self.sync_weights(self.learner_group.get_weights())
        return {f"learner/{k}": v for k, v in metrics.items()} | {
            "num_env_steps_sampled": batch.count
        }


PPOConfig.algo_class = PPO
register_algorithm("PPO", PPO)
