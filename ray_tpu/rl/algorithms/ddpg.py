"""DDPG — deep deterministic policy gradient.

Reference: ``rllib/algorithms/ddpg/`` (Lillicrap et al.; rllib implements
it as the TD3 machinery with the three TD3 tricks switched off). Same
here: DDPG is the TD3 single-pytree jitted step with a single critic
(``twin_q=False``), no target-policy smoothing (``target_noise=0``) and
an actor update every critic step (``policy_delay=1``). Everything else —
deterministic tanh policy, Polyak targets, replay, exploration noise —
is shared with :mod:`ray_tpu.rl.algorithms.td3`.
"""

from __future__ import annotations

from ray_tpu.rl.algorithm import register_algorithm
from ray_tpu.rl.algorithms.td3 import TD3, TD3Config


class DDPGConfig(TD3Config):
    def __init__(self):
        super().__init__()
        self.twin_q = False        # single critic
        self.target_noise = 0.0    # no target policy smoothing by default;
        # the clip stays at TD3's 0.5 so re-enabling target_noise behaves
        # (a 0.0 clip would silently annihilate it)
        self.policy_delay = 1      # actor updates every step

    algo_class = None  # set below


class DDPG(TD3):
    @classmethod
    def get_default_config(cls) -> "DDPGConfig":
        return DDPGConfig()


DDPGConfig.algo_class = DDPG
register_algorithm("DDPG", DDPG)
