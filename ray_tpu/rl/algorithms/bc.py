"""BC — behavior cloning from offline experience.

Reference: ``rllib/algorithms/bc/`` (MARWIL with beta=0: pure supervised
action imitation from an offline dataset). TPU shape: the whole update is
one jitted max-likelihood step over columnar minibatches — no env stepping
in the training path; env runners exist only to evaluate the cloned policy.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.offline import OfflineDataset
from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iter = 100
        #: OfflineDataset | path (.npz / .jsonl) — the training experience
        self.offline_data = None
        #: env steps rolled per iteration to measure the cloned policy
        self.evaluation_steps = 0

    algo_class = None  # set below


def bc_loss(module: ActorCriticModule, params, batch):
    """Negative log-likelihood of dataset actions (+ tiny value-head decay
    so the unused critic cannot drift to inf under weight sharing)."""
    actions = batch[sb.ACTIONS]
    if module.discrete:
        actions = actions.astype(jnp.int32)
    logp, entropy, value = module.logp_entropy_value(params, batch[sb.OBS], actions)
    nll = -jnp.mean(logp)
    return nll + 1e-6 * jnp.mean(value**2), {
        "nll": nll,
        "entropy": jnp.mean(entropy),
    }


class BC(Algorithm):
    @classmethod
    def get_default_config(cls) -> "BCConfig":
        return BCConfig()

    def _setup(self):
        cfg: BCConfig = self.config
        self.dataset: OfflineDataset = OfflineDataset.resolve(
            cfg.offline_data, seed=cfg.seed
        )
        obs_space, act_space = self.foreach_runner("get_spaces")[0]
        spec = RLModuleSpec(obs_space, act_space, hidden=tuple(cfg.hidden))
        self.learner_group = LearnerGroup(
            dict(
                module_factory=lambda: ActorCriticModule(spec),
                loss_fn=bc_loss,
                lr=cfg.lr,
                grad_clip=cfg.grad_clip,
                seed=cfg.seed or 0,
            ),
            remote=cfg.remote_learner,
        )

    def get_weights(self):
        return self.learner_group.get_weights()

    def set_weights(self, params):
        self.learner_group.set_weights(params)
        self.sync_weights(params)

    def training_step(self) -> dict:
        cfg: BCConfig = self.config
        metrics: dict = {}
        for _ in range(cfg.updates_per_iter):
            metrics = self.learner_group.update(self.dataset.sample(cfg.train_batch_size))
        out = {f"learner/{k}": v for k, v in metrics.items()}
        if cfg.evaluation_steps > 0:
            self.sync_weights(self.learner_group.get_weights())
            n_runners = max(1, len(self._runner_actors) or 1)
            n_envs = max(1, cfg.num_envs_per_env_runner)
            per = max(1, cfg.evaluation_steps // (n_runners * n_envs))
            for b in self.foreach_runner("sample_transitions", per):
                self._timesteps_total += b.count
        else:
            self._timesteps_total += cfg.updates_per_iter * cfg.train_batch_size
        return out


BCConfig.algo_class = BC
register_algorithm("BC", BC)
