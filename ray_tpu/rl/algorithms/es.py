"""ES — evolution strategies (ARS variant): gradient-free policy search.

Reference: ``rllib/algorithms/es/`` (Salimans et al. 2017 OpenAI-ES) and
``rllib/algorithms/ars/`` (Mania et al. 2018 Augmented Random Search).
Implemented as the ARS formulation — antithetic (+/-sigma) perturbation
pairs, top-fraction direction selection, reward-std step normalization —
which subsumes plain ES at ``top_frac=1.0``.

TPU-first notes: there is no backward pass at all — the entire "training"
is episode evaluations, so the work distributes as perturbed-weight
rollouts fanned over env-runner ACTORS via the task system (each direction
is two independent ``eval_return`` calls; the only synchronization is the
rank-and-update reduction at the end of the iteration, on the driver).
Policy noise is reproducible from (iteration, direction) seeds, so only
seeds would need to travel in a multi-host variant — here full perturbed
pytrees ship because MLP policies are tiny.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig, register_algorithm


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        #: antithetic direction pairs per iteration (2x this many evals)
        self.num_rollouts = 8
        #: perturbation scale in parameter space
        self.sigma = 0.05
        #: step size
        self.lr = 0.02
        #: fraction of directions (ranked by max(R+, R-)) used in the update
        self.top_frac = 0.5
        #: complete episodes averaged per perturbation evaluation
        self.episodes_per_eval = 1
        #: env-step bound per evaluation (non-terminating policy guard)
        self.eval_max_steps = 2000

    algo_class = None  # set below


class ES(Algorithm):
    @classmethod
    def get_default_config(cls) -> "ESConfig":
        return ESConfig()

    def _setup(self):
        from jax.flatten_util import ravel_pytree

        # the search space is the runner module's full parameter pytree
        # flattened (the unused value head rides along — its perturbations
        # never influence action selection, so they are return-neutral)
        params = self.foreach_runner("get_weights")[0]
        self._theta, self._unravel = ravel_pytree(params)
        self._theta = np.asarray(self._theta, np.float64)
        self._np_rng = np.random.default_rng(self.config.seed or 0)

    def get_weights(self):
        return self._unravel(self._theta.astype(np.float32))

    def set_weights(self, params) -> None:
        from jax.flatten_util import ravel_pytree

        self._theta = np.asarray(ravel_pytree(params)[0], np.float64)
        self.sync_weights(self.get_weights())

    def _eval(self, flat: np.ndarray, runner_idx: int, futures: list) -> None:
        cfg: ESConfig = self.config
        params = self._unravel(flat.astype(np.float32))
        if self._local_runner is not None:
            futures.append(
                self._local_runner.eval_return(
                    params, cfg.episodes_per_eval, cfg.eval_max_steps
                )
            )
        else:
            actor = self._runner_actors[runner_idx % len(self._runner_actors)]
            futures.append(
                actor.eval_return.remote(
                    params, cfg.episodes_per_eval, cfg.eval_max_steps
                )
            )

    def training_step(self) -> dict:
        cfg: ESConfig = self.config
        dim = self._theta.size
        deltas = self._np_rng.standard_normal((cfg.num_rollouts, dim))
        futures: list = []
        for i, delta in enumerate(deltas):
            self._eval(self._theta + cfg.sigma * delta, 2 * i, futures)
            self._eval(self._theta - cfg.sigma * delta, 2 * i + 1, futures)
        if self._local_runner is None:
            results = ray_tpu.get(futures, timeout=600)
        else:
            results = futures
        r_pos = np.array([results[2 * i]["return_mean"] for i in range(cfg.num_rollouts)])
        r_neg = np.array([results[2 * i + 1]["return_mean"] for i in range(cfg.num_rollouts)])
        steps = int(sum(r["steps"] for r in results))
        self._timesteps_total += steps

        # ARS update: rank directions by their best side, keep the top
        # fraction, normalize the step by the kept returns' std
        k = max(1, int(round(cfg.top_frac * cfg.num_rollouts)))
        order = np.argsort(np.maximum(r_pos, r_neg))[::-1][:k]
        kept = np.concatenate([r_pos[order], r_neg[order]])
        r_std = float(kept.std()) or 1.0
        grad = ((r_pos[order] - r_neg[order])[:, None] * deltas[order]).sum(0)
        self._theta = self._theta + cfg.lr / (k * r_std) * grad

        # central-policy evaluation doubles as the weight sync (runners end
        # the iteration holding the updated central weights)
        central: list = []
        for idx in range(max(1, len(self._runner_actors))):
            self._eval(self._theta, idx, central)
        if self._local_runner is None:
            evals = ray_tpu.get(central, timeout=600)
        else:
            evals = central
        rets = [e["return_mean"] for e in evals if e["episodes"]]
        if rets:
            self._episode_return_mean = float(np.mean(rets))
        self._timesteps_total += int(sum(e["steps"] for e in evals))
        return {
            "es_reward_pos_mean": float(r_pos.mean()),
            "es_reward_neg_mean": float(r_neg.mean()),
            "es_reward_std": r_std,
            "es_update_norm": float(np.linalg.norm(cfg.lr / (k * r_std) * grad)),
            "episode_return_central": self._episode_return_mean,
        }


ESConfig.algo_class = ES
register_algorithm("ES", ES)
register_algorithm("ARS", ES)  # same machinery; ARS is the formulation used
