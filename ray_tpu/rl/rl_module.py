"""RLModule: the policy/value network abstraction.

Reference: ``rllib/core/rl_module/rl_module.py`` (framework-agnostic module
with forward_exploration / forward_train). TPU-first: a module is a pair of
pure functions over a parameter pytree — ``init(rng) -> params`` and
``apply(params, obs) -> outputs`` — so the same code jits for a single CPU
worker (env runners) and pjits over a device mesh (learners). No framework
classes to wrap/unwrap; distribution math lives here as jax functions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.spaces import Box, Discrete, Space


def _mlp_init(rng, sizes: list[int], final_scale: float = 0.01):
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, k in enumerate(keys):
        fan_in = sizes[i]
        scale = final_scale if i == len(keys) - 1 else 1.0
        w = jax.random.normal(k, (sizes[i], sizes[i + 1]), jnp.float32) * scale * (fan_in**-0.5)
        b = jnp.zeros((sizes[i + 1],), jnp.float32)
        params.append({"w": w, "b": b})
    return params


def _mlp_apply(params, x, activation=jax.nn.tanh):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = activation(x)
    return x


@dataclasses.dataclass
class RLModuleSpec:
    """Reference: ``rllib/core/rl_module/rl_module.py`` SingleAgentRLModuleSpec."""

    observation_space: Space
    action_space: Space
    hidden: tuple = (64, 64)
    free_log_std: bool = True  # continuous: state-independent log-std
    #: (out_channels, kernel, stride) per conv layer — used automatically
    #: when the observation space is rank-3 (H, W, C) pixels. Convs are the
    #: MXU-native encoder for Atari-class inputs (reference: rllib's
    #: Atari CNN defaults, scaled for small frames).
    conv_filters: tuple = ((16, 4, 2), (32, 4, 2))


def _cnn_init(rng, in_ch: int, filters) -> list:
    params = []
    keys = jax.random.split(rng, len(filters))
    ch = in_ch
    for k, (out_ch, ksz, _stride) in zip(keys, filters):
        fan_in = ksz * ksz * ch
        params.append(
            {
                "w": jax.random.normal(k, (ksz, ksz, ch, out_ch), jnp.float32)
                * np.sqrt(2.0 / fan_in),
                "b": jnp.zeros((out_ch,), jnp.float32),
            }
        )
        ch = out_ch
    return params


def _cnn_apply(params: list, x: jax.Array, filters) -> jax.Array:
    """NHWC conv stack → flat features (SAME padding, ReLU)."""
    for p, (_out, _k, stride) in zip(params, filters):
        x = jax.lax.conv_general_dilated(
            x, p["w"], (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + p["b"]
        x = jax.nn.relu(x)
    return x.reshape(x.shape[0], -1)


class ActorCriticModule:
    """Actor + critic heads; discrete (categorical) or continuous (diagonal
    gaussian). Rank-3 (pixel) observation spaces get a shared CNN encoder
    (conv on the MXU — the Atari-class path); flat spaces use
    shared-nothing MLPs as before."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec
        shape = tuple(spec.observation_space.shape)
        self._conv = len(shape) == 3
        self.discrete = isinstance(spec.action_space, Discrete)
        self.act_dim = (
            spec.action_space.n if self.discrete else int(np.prod(spec.action_space.shape))
        )
        if self._conv:
            h, w = shape[0], shape[1]
            for _out, _k, s in spec.conv_filters:
                h = -(-h // s)
                w = -(-w // s)
            self.obs_dim = h * w * spec.conv_filters[-1][0]  # encoder features
        else:
            self.obs_dim = int(np.prod(shape))

    def init(self, rng: jax.Array) -> dict:
        k_pi, k_v, k_enc = jax.random.split(rng, 3)
        h = list(self.spec.hidden)
        params = {
            "pi": _mlp_init(k_pi, [self.obs_dim] + h + [self.act_dim]),
            "v": _mlp_init(k_v, [self.obs_dim] + h + [1], final_scale=1.0),
        }
        if self._conv:
            params["enc"] = _cnn_init(
                k_enc, self.spec.observation_space.shape[2], self.spec.conv_filters
            )
        if not self.discrete:
            params["log_std"] = jnp.zeros((self.act_dim,), jnp.float32)
        return params

    def _features(self, params: dict, obs: jax.Array) -> jax.Array:
        if self._conv:
            return _cnn_apply(params["enc"], obs, self.spec.conv_filters)
        return obs

    def apply(self, params: dict, obs: jax.Array) -> dict:
        """obs (B, obs_dim) or (B, H, W, C) → {'logits'|'mean'+'log_std',
        'value' (B,)}."""
        feats = self._features(params, obs)
        pi_out = _mlp_apply(params["pi"], feats)
        value = _mlp_apply(params["v"], feats)[..., 0]
        if self.discrete:
            return {"logits": pi_out, "value": value}
        return {"mean": pi_out, "log_std": params["log_std"], "value": value}

    # -- distribution ops (pure jax; used by runners and learners) ---------

    def sample_action(self, params: dict, obs: jax.Array, rng: jax.Array):
        out = self.apply(params, obs)
        if self.discrete:
            action = jax.random.categorical(rng, out["logits"], axis=-1)
            logp = _categorical_logp(out["logits"], action)
        else:
            std = jnp.exp(out["log_std"])
            eps = jax.random.normal(rng, out["mean"].shape)
            action = out["mean"] + eps * std
            logp = _gaussian_logp(out["mean"], out["log_std"], action)
        return action, logp, out["value"]

    def logp_entropy_value(self, params: dict, obs: jax.Array, actions: jax.Array):
        out = self.apply(params, obs)
        if self.discrete:
            logp = _categorical_logp(out["logits"], actions)
            p = jax.nn.softmax(out["logits"], axis=-1)
            entropy = -jnp.sum(p * jnp.log(p + 1e-8), axis=-1)
        else:
            logp = _gaussian_logp(out["mean"], out["log_std"], actions)
            entropy = jnp.sum(out["log_std"] + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1) * jnp.ones(
                out["mean"].shape[:-1]
            )
        return logp, entropy, out["value"]


def _categorical_logp(logits, actions):
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp_all, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]


def _gaussian_logp(mean, log_std, actions):
    std = jnp.exp(log_std)
    return jnp.sum(
        -0.5 * (((actions - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1
    )


class QModule:
    """Q-network (+ target) for DQN-family algorithms."""

    discrete = True

    def __init__(self, spec: RLModuleSpec):
        assert isinstance(spec.action_space, Discrete), "DQN requires a Discrete action space"
        self.spec = spec
        self.obs_dim = int(np.prod(spec.observation_space.shape))
        self.act_dim = spec.action_space.n

    def init(self, rng: jax.Array) -> dict:
        k = jax.random.split(rng, 1)[0]
        h = list(self.spec.hidden)
        q = _mlp_init(k, [self.obs_dim] + h + [self.act_dim], final_scale=1.0)
        return {"q": q, "target_q": jax.tree_util.tree_map(jnp.copy, q)}

    def q_values(self, params: dict, obs: jax.Array, target: bool = False) -> jax.Array:
        return _mlp_apply(params["target_q" if target else "q"], obs, activation=jax.nn.relu)

    def sample_action(self, params: dict, obs: jax.Array, rng: jax.Array):
        """Greedy argmax policy (runners layer ε-greedy on top via
        ``EnvRunner.set_epsilon``); logp/value slots are zeros for interface
        parity with ActorCriticModule."""
        q = self.q_values(params, obs)
        action = jnp.argmax(q, axis=-1)
        zeros = jnp.zeros(action.shape, jnp.float32)
        return action, zeros, zeros
