"""Algorithm + AlgorithmConfig: the RL training driver.

Reference: ``rllib/algorithms/algorithm.py:192`` (Algorithm(Trainable):
``step``/``training_step``, save/restore, evaluate) and
``algorithm_config.py`` (fluent builder: ``.environment().env_runners()
.training()``). An Algorithm owns N EnvRunner actors + a LearnerGroup;
``train()`` = one ``training_step`` plus result bookkeeping; algorithms
register themselves so ``tune.run("PPO")`` resolves by name.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Optional, Type

import numpy as np

import ray_tpu
from ray_tpu.rl.env_runner import EnvRunner
from ray_tpu.rl.sample_batch import SampleBatch

_ALGORITHMS: dict[str, Type["Algorithm"]] = {}


def register_algorithm(name: str, cls: Type["Algorithm"]) -> None:
    _ALGORITHMS[name] = cls


def get_algorithm_class(name: str) -> Type["Algorithm"]:
    if name not in _ALGORITHMS:
        raise KeyError(f"Unknown algorithm {name!r}; registered: {sorted(_ALGORITHMS)}")
    return _ALGORITHMS[name]


class AlgorithmConfig:
    """Fluent builder, ``.build()`` → Algorithm.

    Subset of the reference's surface that the algorithms here consume;
    unknown keys pass through ``.training(**kwargs)`` into ``self.extra``.
    """

    algo_class: Optional[Type["Algorithm"]] = None

    def __init__(self):
        self.env: Any = None
        self.num_env_runners = 0          # 0 = sample in-process (local mode)
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 200
        # Reference: AlgorithmConfig.fault_tolerance(restart_failed_env_runners=)
        # — a dead runner actor is replaced in-place and training continues.
        self.restart_failed_env_runners = True
        #: factories building connector pipelines per runner (reference:
        #: AlgorithmConfig.env_runners(env_to_module_connector=...))
        self.env_to_module_connector = None
        self.module_to_env_connector = None
        self.train_batch_size = 4000
        self.minibatch_size = 128
        self.num_epochs = 8
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed: Optional[int] = 0
        self.hidden = (64, 64)
        self.remote_learner = False
        self.grad_clip: Optional[float] = 0.5
        self.extra: dict[str, Any] = {}

    # builder steps (each returns self, reference style) --------------------

    def environment(self, env=None, **kwargs) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        self.extra.update(kwargs)
        return self

    def env_runners(
        self,
        num_env_runners: Optional[int] = None,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        env_to_module_connector=None,
        module_to_env_connector=None,
        **kwargs,
    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        self.extra.update(kwargs)
        return self

    # reference alias
    rollouts = env_runners

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def debugging(self, seed: Optional[int] = None, **kwargs) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        self.extra.update(kwargs)
        return self

    def framework(self, *_args, **_kwargs) -> "AlgorithmConfig":
        return self  # jax only — kept for call-site parity

    def resources(self, **kwargs) -> "AlgorithmConfig":
        self.extra.update(kwargs)
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> dict:
        return {k: v for k, v in vars(self).items() if not k.startswith("_")}

    def update_from_dict(self, d: dict) -> "AlgorithmConfig":
        return self.training(**d)

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self.env = env
        assert self.algo_class is not None, "Use a concrete config (PPOConfig, DQNConfig)"
        return self.algo_class(self)


class Algorithm:
    """Base driver. Subclasses implement ``_setup()`` and ``training_step()``."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._timesteps_total = 0
        self._episode_return_mean: Optional[float] = None
        self._runner_actors: list = []
        self._local_runner: Optional[EnvRunner] = None
        self._make_runners()
        self._setup()

    # -- runner management (WorkerSet equivalent) ---------------------------

    def _runner_kwargs(self) -> dict:
        return dict(
            env_spec=self.config.env,
            num_envs=self.config.num_envs_per_env_runner,
            rollout_fragment_length=self.config.rollout_fragment_length,
            seed=self.config.seed,
            hidden=tuple(self.config.hidden),
            module_cls=self._module_cls(),
            env_to_module_connector=self.config.env_to_module_connector,
            module_to_env_connector=self.config.module_to_env_connector,
        )

    def _module_cls(self):
        from ray_tpu.rl.rl_module import ActorCriticModule

        return ActorCriticModule

    def _make_runners(self):
        n = self.config.num_env_runners
        if n <= 0:
            self._local_runner = EnvRunner(**self._runner_kwargs())
            return
        cls = ray_tpu.remote(EnvRunner)
        for i in range(n):
            kw = self._runner_kwargs()
            kw["worker_index"] = i
            kw["seed"] = None if self.config.seed is None else self.config.seed + i
            self._runner_actors.append(cls.remote(**kw))
        ray_tpu.get([a.ping.remote() for a in self._runner_actors])

    def foreach_runner(self, method: str, *args) -> list:
        """Fan a method out to all runners (reference:
        ``WorkerSet.foreach_worker`` with fault-tolerant apply). A runner
        that died is restarted in-place (``restart_failed_env_runners``) and
        its result for this round is skipped — mirroring the reference's
        ``FaultAwareApply`` semantics."""
        from ray_tpu.exceptions import RayActorError

        if self._local_runner is not None:
            return [getattr(self._local_runner, method)(*args)]
        futures = [getattr(a, method).remote(*args) for a in self._runner_actors]
        results = []
        for i, f in enumerate(futures):
            try:
                results.append(ray_tpu.get(f))
            except RayActorError:
                if not self.config.restart_failed_env_runners:
                    raise
                self.restart_runner(i)
        if not results:
            raise RuntimeError(f"All {len(futures)} env runners failed in {method!r}")
        return results

    def restart_runner(self, index: int) -> None:
        """Replace a dead runner actor with a fresh one carrying the current
        weights (reference: EnvRunnerGroup._restored_workers path)."""
        try:
            ray_tpu.kill(self._runner_actors[index])
        except Exception as e:
            from ray_tpu._private.log_util import warn_throttled

            # usually already dead (that's why it's being replaced)
            warn_throttled("rl algorithm: runner kill", e)
        cls = ray_tpu.remote(EnvRunner)
        kw = self._runner_kwargs()
        kw["worker_index"] = index
        kw["seed"] = None if self.config.seed is None else self.config.seed + index
        actor = cls.remote(**kw)
        try:
            weights = self.get_weights()
        except (AttributeError, NotImplementedError):
            weights = None  # during _setup, before the learner exists
        if weights is not None:
            actor.set_weights.remote(weights)
        # stateful connectors (running normalizers) must not restart cold:
        # clone state from any surviving runner
        if self.config.env_to_module_connector or self.config.module_to_env_connector:
            for j, other in enumerate(self._runner_actors):
                if j == index:
                    continue
                try:
                    state = ray_tpu.get(other.get_connector_state.remote(), timeout=10)
                    actor.set_connector_state.remote(state)
                    break
                except Exception as e:
                    from ray_tpu._private.log_util import warn_throttled

                    # this donor may be dead too — try the next survivor,
                    # but don't let every-donor-failing go unreported (the
                    # new runner would restart with cold normalizer state)
                    warn_throttled("rl algorithm: connector-state clone", e)
                    continue
        self._runner_actors[index] = actor

    def sync_weights(self, params) -> None:
        self.foreach_runner("set_weights", params)

    # -- Trainable surface --------------------------------------------------

    def _setup(self):
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    def train(self) -> dict:
        t0 = time.time()
        result = self.training_step()
        self.iteration += 1
        stats = [s for s in self.foreach_runner("episode_stats") if s["episodes"]]
        if stats:
            self._episode_return_mean = float(
                np.average(
                    [s["episode_return_mean"] for s in stats],
                    weights=[s["episodes"] for s in stats],
                )
            )
        result.update(
            {
                "training_iteration": self.iteration,
                "episode_return_mean": self._episode_return_mean,
                # reference's legacy key name, used by its tuned examples
                "episode_reward_mean": self._episode_return_mean,
                "num_env_steps_sampled_lifetime": self._timesteps_total,
                "timesteps_total": self._timesteps_total,
                "time_this_iter_s": time.time() - t0,
            }
        )
        return result

    def stop(self):
        from ray_tpu._private.log_util import warn_throttled

        for a in self._runner_actors:
            try:
                ray_tpu.kill(a)
            except Exception as e:
                # best-effort teardown, but leaking runner actors on every
                # stop must not be silent
                warn_throttled("rl algorithm: runner kill", e)
        lg = getattr(self, "learner_group", None)
        if lg is not None:
            lg.shutdown()

    # -- checkpointing ------------------------------------------------------

    def get_state(self) -> dict:
        return {
            "weights": self.get_weights(),
            "iteration": self.iteration,
            "timesteps": self._timesteps_total,
        }

    def set_state(self, state: dict) -> None:
        self.set_weights(state["weights"])
        self.iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps", 0)

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, params):
        raise NotImplementedError

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(self.get_state(), f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            self.set_state(pickle.load(f))

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig) -> Callable:
        """Function trainable for ray_tpu.tune: loops train() forever,
        reporting each iteration with a checkpoint (reference: Algorithm IS
        a class Trainable; tune here runs function trainables)."""

        def trainable(config: dict):
            import tempfile

            from ray_tpu import tune
            from ray_tpu.train import Checkpoint

            cfg = base_config.copy().update_from_dict(config or {})
            algo = cfg.build()
            ckpt = tune.get_checkpoint()
            if ckpt:
                algo.restore(ckpt.path)
            try:
                while True:
                    result = algo.train()
                    d = tempfile.mkdtemp(prefix="rl_ckpt_")
                    algo.save(d)
                    tune.report(result, checkpoint=Checkpoint(d))
            finally:
                algo.stop()

        trainable.__name__ = cls.__name__
        return trainable
