"""Multi-agent environments with shared-policy training.

Reference: ``rllib/env/multi_agent_env.py`` — envs whose ``reset``/``step``
speak per-agent dicts (``{agent_id: obs}``, dones keyed per agent plus
``"__all__"``). TPU-first integration: ``MultiAgentVectorEnv`` flattens
(env, agent) pairs into vector SLOTS with the same stacked-array interface
as ``SyncVectorEnv``, so the jitted policy sees one batched forward over all
agents of all envs and every single-agent algorithm (PPO/IMPALA/...) trains
a SHARED policy across agents with zero algorithm changes (the reference's
default when all agents map to one policy).

Scope: fixed agent sets (every agent steps every turn until ``__all__``).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ray_tpu.rl.spaces import Space


class MultiAgentEnv:
    """Per-agent-dict env API (reference: ``multi_agent_env.py``)."""

    #: fixed agent ids, e.g. ["agent_0", "agent_1"]
    agents: list
    observation_space: Space  # per-agent (homogeneous, shared policy)
    action_space: Space

    def reset(self, *, seed: Optional[int] = None) -> tuple[dict, dict]:
        raise NotImplementedError

    def step(self, action_dict: dict) -> tuple[dict, dict, dict, dict, dict]:
        """returns (obs, rewards, terminateds, truncateds, infos) — all
        per-agent dicts; terminateds/truncateds include '__all__'."""
        raise NotImplementedError

    def close(self):
        pass


class MultiAgentVectorEnv:
    """SyncVectorEnv-shaped view over N multi-agent envs: slot (i, a) is
    agent ``a`` of env ``i``; ``n = n_envs * n_agents``. Episodes reset when
    ``__all__`` is set; the pre-reset obs is reported as ``final_obs``."""

    def __init__(self, creator, n_envs: int, seed: Optional[int] = None):
        from ray_tpu.rl.env import make_env

        self.envs = [make_env(creator) for _ in range(n_envs)]
        first = self.envs[0]
        assert isinstance(first, MultiAgentEnv), type(first)
        self.agents = list(first.agents)
        self.n_envs = n_envs
        self.n = n_envs * len(self.agents)
        self.observation_space = first.observation_space
        self.action_space = first.action_space
        self._seed = seed

    def _stack(self, dicts: list[dict], default=0.0):
        rows = []
        for d in dicts:
            for a in self.agents:
                rows.append(d.get(a, default))
        return rows

    def reset(self):
        obs = []
        for i, e in enumerate(self.envs):
            o, _ = e.reset(seed=None if self._seed is None else self._seed + i)
            obs.extend(o[a] for a in self.agents)
        return np.stack(obs)

    def step(self, actions):
        A = len(self.agents)
        obs_out, rew_out, term_out, trunc_out, final_out = [], [], [], [], []
        for i, e in enumerate(self.envs):
            act = {a: actions[i * A + j] for j, a in enumerate(self.agents)}
            o, r, term, trunc, _info = e.step(act)
            done_all = term.get("__all__", False) or trunc.get("__all__", False)
            finals = [o.get(a) for a in self.agents]
            if done_all:
                o, _ = e.reset()
            for j, a in enumerate(self.agents):
                obs_out.append(o[a])
                rew_out.append(r.get(a, 0.0))
                term_out.append(bool(term.get(a, term.get("__all__", False))))
                trunc_out.append(bool(trunc.get(a, trunc.get("__all__", False))))
                final_out.append(finals[j] if finals[j] is not None else o[a])
        return (
            np.stack(obs_out),
            np.asarray(rew_out, np.float32),
            np.asarray(term_out, bool),
            np.asarray(trunc_out, bool),
            np.stack(final_out),
        )


class EchoCoopEnv(MultiAgentEnv):
    """Tiny 2-agent cooperative debug env: each step both agents see the same
    random bit and are rewarded for choosing the action equal to it (and
    extra when BOTH match — coordination signal). Fixed-length episodes."""

    def __init__(self, episode_len: int = 32):
        from ray_tpu.rl.spaces import Box, Discrete

        self.agents = ["agent_0", "agent_1"]
        self.observation_space = Box(0.0, 1.0, shape=(2,))
        self.action_space = Discrete(2)
        self.episode_len = episode_len
        self._rng = np.random.default_rng()
        self._bit = 0
        self._t = 0

    def _obs(self):
        o = np.array([self._bit, 1 - self._bit], np.float32)
        return {a: o for a in self.agents}

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._bit = int(self._rng.integers(0, 2))
        self._t = 0
        return self._obs(), {}

    def step(self, action_dict):
        correct = {a: int(action_dict[a]) == self._bit for a in self.agents}
        both = all(correct.values())
        rewards = {
            a: (1.0 if correct[a] else 0.0) + (0.5 if both else 0.0)
            for a in self.agents
        }
        self._t += 1
        self._bit = int(self._rng.integers(0, 2))
        trunc_all = self._t >= self.episode_len
        terms = {a: False for a in self.agents} | {"__all__": False}
        truncs = {a: trunc_all for a in self.agents} | {"__all__": trunc_all}
        return self._obs(), rewards, terms, truncs, {}
