"""Learner / LearnerGroup: the gradient-update half of the RL loop.

Reference: ``rllib/core/learner/learner.py:105`` (per-algorithm loss over an
RLModule + optimizer) and ``learner_group.py:71`` (N learner actors with
DDP-wrapped modules). TPU-first inversion: instead of one learner actor per
GPU with NCCL DDP, ONE learner process drives all local chips — the update
is a single pjit'd function whose batch dimension is sharded over the mesh's
``data`` axis, so the gradient allreduce compiles to an ICI psum inside the
step (the XLA-native counterpart of DDP). A LearnerGroup can still place
that learner in a remote actor to keep sampling and learning on different
hosts.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rl.sample_batch import SampleBatch


class Learner:
    """Owns params + optimizer state; `update(batch)` is one jitted step.

    ``loss_fn(module, params, batch_dict) -> (loss, metrics_dict)`` is
    supplied by the algorithm (PPO/DQN/...); everything else (adam, grad
    clip, device mesh sharding) is shared machinery.
    """

    def __init__(
        self,
        module_factory: Callable[[], Any],
        loss_fn: Callable,
        lr: float = 3e-4,
        grad_clip: Optional[float] = 0.5,
        seed: int = 0,
        data_parallel: bool = True,
    ):
        import jax
        import optax

        self.module = module_factory()
        self._rng = jax.random.PRNGKey(seed)
        self.params = self.module.init(self._rng)
        self.tx = (
            optax.chain(optax.clip_by_global_norm(grad_clip), optax.adam(lr))
            if grad_clip
            else optax.adam(lr)
        )
        self.opt_state = self.tx.init(self.params)
        self._loss_fn = loss_fn
        self._sharding = None
        if data_parallel and len(jax.devices()) > 1:
            # Shard the batch over all addressable devices; params replicate.
            # XLA inserts the gradient psum over the mesh automatically.
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(np.array(jax.devices()), ("data",))
            self._sharding = NamedSharding(mesh, P("data"))
            self._replicated = NamedSharding(mesh, P())
        # No buffer donation: freshly-initialized params and adam state can
        # alias the same cached zero constant, and donating an aliased buffer
        # twice is an XLA error. RL nets are small; donation buys nothing.
        self._update = jax.jit(self._update_impl)
        self._grads = jax.jit(self._grads_impl)
        self._apply_tx = jax.jit(self._apply_impl)

    def _apply_impl(self, params, opt_state, grads):
        import optax

        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def _grads_impl(self, params, batch):
        import jax

        def loss_wrap(p):
            return self._loss_fn(self.module, p, batch)

        (loss, metrics), grads = jax.value_and_grad(loss_wrap, has_aux=True)(params)
        return grads, {"loss": loss, **metrics}

    def compute_grads(self, batch: SampleBatch):
        """Gradients of the loss on this learner's batch shard, WITHOUT
        applying them — the data-parallel LearnerGroup averages shard
        grads across learners before anyone applies (reference:
        learner_group.py DDP semantics)."""
        import jax

        rows = batch.count
        dev_batch = self._device_batch(batch)
        grads, metrics = self._grads(self.params, dev_batch)
        # ONE host transfer for the whole metrics pytree (not one sync per
        # entry): same contract as update() — per-sample aux arrays (e.g.
        # DQN |td| for prioritized replay) pass through, padding trimmed
        host = jax.device_get(metrics)
        out = {
            k: (float(v) if np.ndim(v) == 0 else v[:rows]) for k, v in host.items()
        }
        return jax.device_get(grads), out

    def apply_grads(self, grads) -> bool:
        import jax

        if self._sharding is not None:
            grads = jax.tree_util.tree_map(
                lambda g: jax.device_put(g, self._replicated), grads
            )
        self.params, self.opt_state = self._apply_tx(self.params, self.opt_state, grads)
        return True

    def _update_impl(self, params, opt_state, batch):
        import jax

        def loss_wrap(p):
            return self._loss_fn(self.module, p, batch)

        (loss, metrics), grads = jax.value_and_grad(loss_wrap, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm, **metrics}

    def _device_batch(self, batch: SampleBatch):
        import jax

        arrays = {k: np.asarray(v) for k, v in batch.items()}
        if self._sharding is not None:
            n = len(jax.devices())
            # Pad (by cycling rows) to a multiple of the data axis so the
            # shard is even — works even when the batch is SMALLER than the
            # device count (e.g. few-env IMPALA sequence batches).
            rows = len(next(iter(arrays.values())))
            target = -(-rows // n) * n
            if target != rows:
                idx = np.arange(target) % rows
                arrays = {k: v[idx] for k, v in arrays.items()}
            return {k: jax.device_put(v, self._sharding) for k, v in arrays.items()}
        return {k: jax.device_put(v) for k, v in arrays.items()}

    def update(self, batch: SampleBatch) -> dict:
        import jax

        rows = batch.count
        dev_batch = self._device_batch(batch)
        self.params, self.opt_state, metrics = self._update(self.params, self.opt_state, dev_batch)
        # ONE host transfer for the whole metrics pytree — per-entry
        # np.asarray would stall the XLA pipeline once per metric.
        # Per-sample aux outputs (e.g. DQN |td| for prioritized replay)
        # pass through as arrays, trimmed of any data-axis padding rows.
        host = jax.device_get(metrics)
        return {
            k: (float(v) if np.ndim(v) == 0 else v[:rows]) for k, v in host.items()
        }

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> bool:
        self.params = params
        return True

    def apply(self, fn: Callable, *args) -> Any:
        """Run an arbitrary function against (learner, *args) — the remote
        escape hatch LearnerGroup uses for target-net sync etc."""
        return fn(self, *args)


class LearnerGroup:
    """Places the Learner locally or in a remote actor.

    Reference: ``rllib/core/learner/learner_group.py:71``. ``remote=True``
    puts the learner (and therefore the device mesh) in its own process so
    env runners and the driver never contend with the update stream.
    """

    def __init__(
        self,
        learner_kwargs: dict,
        remote: bool = False,
        num_cpus: float = 1,
        num_learners: int = 1,
    ):
        self._remote = remote or num_learners > 1
        self._actors: list = []
        if num_learners > 1:
            # data-parallel learners (reference: learner_group.py:71 N
            # DDP-wrapped learners): every learner initializes IDENTICAL
            # params from the shared seed, each update computes gradients
            # on its batch shard, the group averages (sample-weighted) and
            # every learner applies the SAME averaged update — weights stay
            # bit-identical across learners, exactly like DDP.
            import ray_tpu

            cls = ray_tpu.remote(Learner)
            self._actors = [
                cls.options(num_cpus=num_cpus).remote(**learner_kwargs)
                for _ in range(num_learners)
            ]
            self._actor = self._actors[0]
            self._local = None
        elif remote:
            import ray_tpu

            cls = ray_tpu.remote(Learner)
            self._actor = cls.options(num_cpus=num_cpus).remote(**learner_kwargs)
            self._actors = [self._actor]
            self._local = None
        else:
            self._actor = None
            self._local = Learner(**learner_kwargs)

    def update(self, batch: SampleBatch) -> dict:
        if self._local is not None:
            return self._local.update(batch)
        import ray_tpu

        if len(self._actors) > 1:
            return self._update_data_parallel(batch)
        return ray_tpu.get(self._actor.update.remote(batch))

    def _update_data_parallel(self, batch: SampleBatch) -> dict:
        """Exact full-batch equivalence holds when each shard's row count
        divides the learner's local device count (otherwise
        _device_batch's cycle-padding double-weights a few rows — the same
        bounded bias DDP accepts for uneven final batches)."""
        import jax
        import ray_tpu

        k = len(self._actors)
        n = batch.count
        bounds = [round(i * n / k) for i in range(k + 1)]
        # a 0-row shard would mean a loss over zero elements → NaN grads
        # that no zero weight can neutralize (0·NaN = NaN): only learners
        # with actual rows compute this round; EVERY learner still applies
        # the same averaged update (lockstep invariant)
        work = [
            (a, batch.slice(lo, hi), (hi - lo) / max(n, 1))
            for a, lo, hi in zip(self._actors, bounds, bounds[1:])
            if hi > lo
        ]
        grad_refs = [a.compute_grads.remote(s) for a, s, _w in work]
        results = ray_tpu.get(grad_refs)
        weights = [w for _a, _s, w in work]
        # sample-weighted average == the full-batch gradient of a mean loss
        avg = jax.tree_util.tree_map(
            lambda *gs: sum(w * g for w, g in zip(weights, gs)),
            *[g for g, _m in results],
        )
        ray_tpu.get([a.apply_grads.remote(avg) for a in self._actors])
        metrics: dict = {}
        arrays: dict = {}
        # compute_grads already device_get-s its metrics: everything here
        # is host numpy, no per-entry device sync
        for w, (_g, m) in zip(weights, results):
            for key, v in m.items():
                if np.ndim(v) == 0:
                    metrics[key] = metrics.get(key, 0.0) + w * float(v)
                else:
                    arrays.setdefault(key, []).append(v)
        for key, parts in arrays.items():
            # per-sample aux (e.g. DQN |td|) re-assembles in shard order so
            # prioritized-replay priority updates keep working under DP
            metrics[key] = np.concatenate(parts)
        return metrics

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        import ray_tpu

        return ray_tpu.get(self._actor.get_weights.remote())

    def set_weights(self, params):
        if self._local is not None:
            return self._local.set_weights(params)
        import ray_tpu

        # all learners must stay in lockstep (DDP invariant)
        return ray_tpu.get([a.set_weights.remote(params) for a in self._actors])[0]

    def apply(self, fn: Callable, *args):
        if self._local is not None:
            return self._local.apply(fn, *args)
        import ray_tpu

        # e.g. target-net sync: runs on EVERY learner; rank0's result returns
        return ray_tpu.get([a.apply.remote(fn, *args) for a in self._actors])[0]

    def shutdown(self):
        import ray_tpu

        from ray_tpu._private.log_util import warn_throttled

        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception as e:
                # best-effort teardown, but not silent: a failed kill here
                # is a leaked learner actor holding its device allocation
                warn_throttled("rl learner group teardown", e)
