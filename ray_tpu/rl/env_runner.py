"""EnvRunner: rollout collection (the sampling half of the RL loop).

Reference: ``rllib/env/single_agent_env_runner.py`` + the older
``RolloutWorker`` (``rllib/evaluation/rollout_worker.py:159``). One runner
drives a vectorized env with ONE jitted policy call per vector step; N
runners are spawned as ray_tpu actors by the algorithm and sampled in
parallel (``WorkerSet.foreach_worker`` equivalent is a list of futures).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rl.env import SyncVectorEnv, make_env
from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec
from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.sample_batch import SampleBatch


class EnvRunner:
    """Collects fixed-length rollout fragments with policy outputs attached.

    Used both in-process (local mode / unit tests) and as an actor body.
    """

    def __init__(
        self,
        env_spec: Any,
        num_envs: int = 1,
        rollout_fragment_length: int = 200,
        seed: Optional[int] = None,
        hidden: tuple = (64, 64),
        worker_index: int = 0,
        module_cls: Callable = ActorCriticModule,
    ):
        import jax

        self.vec = SyncVectorEnv(env_spec, num_envs, seed=seed)
        self.fragment = rollout_fragment_length
        self.spec = RLModuleSpec(self.vec.observation_space, self.vec.action_space, hidden=hidden)
        self.module = module_cls(self.spec)
        self._rng = jax.random.PRNGKey(0 if seed is None else seed + 1000 * worker_index)
        self.params = self.module.init(self._rng)
        self._sample_fn = jax.jit(self.module.sample_action)
        self._obs = self.vec.reset()
        # episode stats
        self._ep_ret = np.zeros(num_envs, np.float32)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed: list[tuple[float, int]] = []

    # -- weights -----------------------------------------------------------

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> bool:
        self.params = params
        return True

    def get_spaces(self):
        return self.spec.observation_space, self.spec.action_space

    # -- sampling ----------------------------------------------------------

    def sample(self, num_steps: Optional[int] = None) -> SampleBatch:
        """Returns a (T*N,)-flattened SampleBatch with advantages computed.

        Keeps (T, N) structure internally so GAE can bootstrap per-env.
        """
        import jax

        T = num_steps or self.fragment
        N = self.vec.n
        obs_buf = np.zeros((T, N) + self.vec.observation_space.shape, np.float32)
        act_shape = () if self.module.discrete else self.vec.action_space.shape
        act_buf = np.zeros((T, N) + act_shape, np.float32 if not self.module.discrete else np.int64)
        rew_buf = np.zeros((T, N), np.float32)
        term_buf = np.zeros((T, N), bool)
        trunc_buf = np.zeros((T, N), bool)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)

        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            action, logp, value = self._sample_fn(self.params, self._obs, key)
            action = np.asarray(action)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            step_actions = action if self.module.discrete else np.asarray(action)
            self._obs, rew, term, trunc = self.vec.step(step_actions)
            rew_buf[t], term_buf[t], trunc_buf[t] = rew, term, trunc
            self._ep_ret += rew
            self._ep_len += 1
            done = term | trunc
            for i in np.nonzero(done)[0]:
                self._completed.append((float(self._ep_ret[i]), int(self._ep_len[i])))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0

        # Bootstrap values for the final obs.
        self._rng, key = jax.random.split(self._rng)
        _, _, last_values = self._sample_fn(self.params, self._obs, key)
        adv, targets = sb.compute_gae(
            rew_buf, val_buf, term_buf, trunc_buf, np.asarray(last_values)
        )
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return SampleBatch(
            {
                sb.OBS: flat(obs_buf),
                sb.ACTIONS: flat(act_buf),
                sb.REWARDS: flat(rew_buf),
                sb.TERMINATEDS: flat(term_buf),
                sb.TRUNCATEDS: flat(trunc_buf),
                sb.LOGP: flat(logp_buf),
                sb.VF_PREDS: flat(val_buf),
                sb.ADVANTAGES: flat(adv),
                sb.VALUE_TARGETS: flat(targets),
            }
        )

    def sample_transitions(self, num_steps: int) -> SampleBatch:
        """(s, a, r, s', done) tuples for off-policy algos (DQN)."""
        import jax

        N = self.vec.n
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.NEXT_OBS, sb.TERMINATEDS)}
        for _ in range(num_steps):
            self._rng, key = jax.random.split(self._rng)
            action, _, _ = self._sample_fn(self.params, self._obs, key)
            action = np.asarray(action)
            prev_obs = self._obs
            self._obs, rew, term, trunc = self.vec.step(action)
            rows[sb.OBS].append(prev_obs)
            rows[sb.ACTIONS].append(action)
            rows[sb.REWARDS].append(rew)
            rows[sb.NEXT_OBS].append(self._obs)
            rows[sb.TERMINATEDS].append(term)
            self._ep_ret += rew
            self._ep_len += 1
            done = term | trunc
            for i in np.nonzero(done)[0]:
                self._completed.append((float(self._ep_ret[i]), int(self._ep_len[i])))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
        return SampleBatch({k: np.concatenate(v) for k, v in rows.items()})

    def set_epsilon(self, eps: float) -> bool:
        """ε-greedy override used by DQN runners (wraps sample_action)."""
        import jax

        base = self.module.sample_action

        def eps_greedy(params, obs, rng):
            action, logp, value = base(params, obs, rng)
            k1, k2 = jax.random.split(jax.random.fold_in(rng, 7))
            import jax.numpy as jnp

            rand_a = jax.random.randint(k1, action.shape, 0, self.module.act_dim)
            explore = jax.random.uniform(k2, action.shape) < eps
            return jnp.where(explore, rand_a, action), logp, value

        self._sample_fn = jax.jit(eps_greedy)
        return True

    def episode_stats(self, clear: bool = True) -> dict:
        eps = self._completed
        if clear:
            self._completed = []
        if not eps:
            return {"episodes": 0, "episode_return_mean": None, "episode_len_mean": None}
        rets = [r for r, _ in eps]
        lens = [l for _, l in eps]
        return {
            "episodes": len(eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def ping(self) -> bool:
        return True
