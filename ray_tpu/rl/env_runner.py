"""EnvRunner: rollout collection (the sampling half of the RL loop).

Reference: ``rllib/env/single_agent_env_runner.py`` + the older
``RolloutWorker`` (``rllib/evaluation/rollout_worker.py:159``). One runner
drives a vectorized env with ONE jitted policy call per vector step; N
runners are spawned as ray_tpu actors by the algorithm and sampled in
parallel (``WorkerSet.foreach_worker`` equivalent is a list of futures).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rl.env import SyncVectorEnv, make_env
from ray_tpu.rl.rl_module import ActorCriticModule, RLModuleSpec
from ray_tpu.rl import sample_batch as sb
from ray_tpu.rl.sample_batch import SampleBatch


class EnvRunner:
    """Collects fixed-length rollout fragments with policy outputs attached.

    Used both in-process (local mode / unit tests) and as an actor body.
    """

    def __init__(
        self,
        env_spec: Any,
        num_envs: int = 1,
        rollout_fragment_length: int = 200,
        seed: Optional[int] = None,
        hidden: tuple = (64, 64),
        worker_index: int = 0,
        module_cls: Callable = ActorCriticModule,
        env_to_module_connector: Optional[Callable] = None,
        module_to_env_connector: Optional[Callable] = None,
    ):
        import jax

        from ray_tpu.rl.env import make_vector_env

        self.vec = make_vector_env(env_spec, num_envs, seed=seed)
        self.fragment = rollout_fragment_length
        self._c_obs = env_to_module_connector() if env_to_module_connector else None
        self._c_act = module_to_env_connector() if module_to_env_connector else None
        obs_space = self.vec.observation_space
        if self._c_obs is not None:
            # the module consumes TRANSFORMED observations: derive its input
            # space (shape may change — flatten/stack connectors) so runner
            # and learner modules agree
            probe = self._c_obs.transform(
                np.zeros((1,) + tuple(obs_space.shape), np.float32)
            )
            from ray_tpu.rl.spaces import Box as _Box

            obs_space = _Box(-np.inf, np.inf, shape=tuple(np.asarray(probe).shape[1:]))
        self.spec = RLModuleSpec(obs_space, self.vec.action_space, hidden=hidden)
        self.module = module_cls(self.spec)
        self._rng = jax.random.PRNGKey(0 if seed is None else seed + 1000 * worker_index)
        self.params = self.module.init(self._rng)
        # Jitted once each; epsilon is a TRACED argument of the eps-greedy
        # variant so updating it never triggers an XLA recompile.
        self._base_fn = jax.jit(self.module.sample_action)
        self._eps_fn = None  # built lazily on first set_epsilon
        self._eps: Optional[float] = None
        self._obs = self._obs_transform(self.vec.reset())
        # episode stats
        # sized by SLOTS (= envs, or envs x agents for multi-agent vectors)
        self._ep_ret = np.zeros(self.vec.n, np.float32)
        self._ep_len = np.zeros(self.vec.n, np.int64)
        self._completed: list[tuple[float, int]] = []

    # -- weights -----------------------------------------------------------

    def get_weights(self):
        return self.params

    def set_weights(self, params) -> bool:
        self.params = params
        return True

    def get_spaces(self):
        return self.spec.observation_space, self.spec.action_space

    # -- connectors --------------------------------------------------------

    def _obs_transform(self, obs, update: bool = True):
        if self._c_obs is None:
            return obs
        if update:
            return self._c_obs(obs)
        return self._c_obs.transform(obs)

    def _act_transform(self, act):
        return self._c_act(act) if self._c_act is not None else act

    def get_connector_state(self) -> dict:
        return {
            "env_to_module": self._c_obs.get_state() if self._c_obs else {},
            "module_to_env": self._c_act.get_state() if self._c_act else {},
        }

    def set_connector_state(self, state: dict) -> bool:
        if self._c_obs and state.get("env_to_module"):
            self._c_obs.set_state(state["env_to_module"])
        if self._c_act and state.get("module_to_env"):
            self._c_act.set_state(state["module_to_env"])
        return True

    # -- policy invocation -------------------------------------------------

    def _policy(self, params, obs, key):
        if self._eps is None:
            return self._base_fn(params, obs, key)
        return self._eps_fn(params, obs, key, self._eps)

    def _values_of(self, obs_batch: np.ndarray) -> np.ndarray:
        """Critic value of arbitrary observations (used to bootstrap at
        truncations from the TRUE final obs rather than the reset obs)."""
        import jax

        _, _, values = self._base_fn(self.params, obs_batch, jax.random.PRNGKey(0))
        return np.asarray(values)

    # -- sampling ----------------------------------------------------------

    def _rollout(self, T: int) -> dict[str, np.ndarray]:
        """Shared (T, N)-buffer rollout collector behind all three samplers.

        Steps the vector env T times with the current policy, maintaining
        episode-return bookkeeping. ``final`` holds each transition's TRUE
        next obs (pre-auto-reset for done envs).
        """
        import jax

        N = self.vec.n
        # transformed shape: connectors may reshape observations
        obs_shape = tuple(np.asarray(self._obs).shape[1:])
        act_shape = () if self.module.discrete else self.vec.action_space.shape
        act_dtype = np.int64 if self.module.discrete else np.float32
        buf = {
            "obs": np.zeros((T, N) + obs_shape, np.float32),
            "act": np.zeros((T, N) + act_shape, act_dtype),
            # the action the ENV executed (post module_to_env transform) —
            # replay/off-policy batches must pair returns with THIS action;
            # the pre-transform module action is only for on-policy logp
            "env_act": np.zeros((T, N) + act_shape, act_dtype),
            "rew": np.zeros((T, N), np.float32),
            "term": np.zeros((T, N), bool),
            "trunc": np.zeros((T, N), bool),
            "logp": np.zeros((T, N), np.float32),
            "val": np.zeros((T, N), np.float32),
            "final": np.zeros((T, N) + obs_shape, np.float32),
        }
        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            # ONE batched device→host transfer per env step (the step is
            # inherently host-synchronous — the vector env needs concrete
            # actions — but three per-array syncs stalled the pipeline
            # three times for one round trip's worth of data)
            action, logp, value = jax.device_get(  # raylint: disable=RL006
                self._policy(self.params, self._obs, key)
            )
            buf["obs"][t] = self._obs
            buf["act"][t] = action
            buf["logp"][t] = logp
            buf["val"][t] = value
            env_action = self._act_transform(action)
            buf["env_act"][t] = env_action
            self._obs, rew, term, trunc, final = self.vec.step(env_action)
            # `final` (each transition's TRUE next obs) transforms FIRST,
            # against the PRE-step connector state: frame stacks peek the
            # stack the slot would have — correct NEXT_OBS for off-policy
            # targets even at episode ends
            tf = getattr(self._c_obs, "transform_final", None) or getattr(
                self._c_obs, "peek", None  # a bare FrameStack connector
            )
            if tf is not None:
                buf["final"][t] = tf(final)
            else:
                buf["final"][t] = self._obs_transform(final, update=False)
            # stateful frame connectors (FrameStack) must learn about
            # episode ends BEFORE transforming the post-step obs: done
            # slots' next frame is a reset frame and starts a fresh stack
            if self._c_obs is not None:
                fn = getattr(self._c_obs, "observe_dones", None)
                if fn is not None:
                    fn(term | trunc)
            # stats-updating transform runs ONCE per step (on the stepped
            # obs); `final` — the same raw data for non-done slots — applies
            # the transform without re-updating running statistics
            self._obs = self._obs_transform(self._obs)
            buf["rew"][t], buf["term"][t], buf["trunc"][t] = rew, term, trunc
            self._ep_ret += rew
            self._ep_len += 1
            for i in np.nonzero(term | trunc)[0]:
                self._completed.append((float(self._ep_ret[i]), int(self._ep_len[i])))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
        return buf

    def _truncation_values(self, buf) -> Optional[np.ndarray]:
        """Critic values of the true final obs, (T, N), where truncated."""
        if not buf["trunc"].any():
            return None
        T, N = buf["rew"].shape
        obs_shape = buf["final"].shape[2:]
        tv = self._values_of(buf["final"].reshape((T * N,) + tuple(obs_shape)))
        return tv.reshape(T, N)

    def sample(self, num_steps: Optional[int] = None) -> SampleBatch:
        """Returns a (T*N,)-flattened SampleBatch with advantages computed.

        Keeps (T, N) structure internally so GAE can bootstrap per-env.
        """
        import jax

        T = num_steps or self.fragment
        N = self.vec.n
        buf = self._rollout(T)
        # Bootstrap values for the final obs.
        self._rng, key = jax.random.split(self._rng)
        _, _, last_values = self._base_fn(self.params, self._obs, key)
        # At truncated steps GAE must bootstrap from the critic's value of
        # the TRUE final obs (pre-reset), not the stored value of the reset
        # obs; one extra batched forward over the rollout supplies it.
        adv, targets = sb.compute_gae(
            buf["rew"], buf["val"], buf["term"], buf["trunc"], np.asarray(last_values),
            truncation_values=self._truncation_values(buf),
        )
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return SampleBatch(
            {
                sb.OBS: flat(buf["obs"]),
                sb.ACTIONS: flat(buf["act"]),
                sb.REWARDS: flat(buf["rew"]),
                sb.TERMINATEDS: flat(buf["term"]),
                sb.TRUNCATEDS: flat(buf["trunc"]),
                sb.LOGP: flat(buf["logp"]),
                sb.VF_PREDS: flat(buf["val"]),
                sb.ADVANTAGES: flat(adv),
                sb.VALUE_TARGETS: flat(targets),
            }
        )

    def sample_transitions(self, num_steps: int) -> SampleBatch:
        """(s, a, r, s', done) tuples for off-policy algos (DQN).

        NEXT_OBS is the TRUE next observation (the pre-reset terminal obs for
        done envs), so Q-targets never bootstrap from a reset state; TRUNCATEDS
        is stored so losses can distinguish time-limit cuts from termination.
        """
        T, N = num_steps, self.vec.n
        buf = self._rollout(T)
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return SampleBatch(
            {
                sb.OBS: flat(buf["obs"]),
                sb.ACTIONS: flat(buf["env_act"]),
                sb.REWARDS: flat(buf["rew"]),
                sb.NEXT_OBS: flat(buf["final"]),
                sb.TERMINATEDS: flat(buf["term"]),
                sb.TRUNCATEDS: flat(buf["trunc"]),
            }
        )

    def sample_sequences(self, num_steps: Optional[int] = None, gamma: float = 0.99) -> SampleBatch:
        """Time-major rollout kept as (N, T, ...) sequences for V-trace
        (IMPALA). Truncated steps fold the critic's value of the true final
        obs into the reward (the standard time-limit bootstrap trick), so the
        V-trace scan can treat every boundary as a hard cut.

        Extra keys: ``bootstrap_value`` (N,) — critic value of the obs after
        the last step of each slot.
        """
        import jax

        T = num_steps or self.fragment
        buf = self._rollout(T)
        rew, done = buf["rew"], buf["term"]
        tv = self._truncation_values(buf)
        if tv is not None:
            rew = np.where(buf["trunc"], rew + gamma * tv, rew)
            done = done | buf["trunc"]
        self._rng, key = jax.random.split(self._rng)
        _, _, boot = self._base_fn(self.params, self._obs, key)
        tm = lambda a: np.swapaxes(a, 0, 1)  # (T,N,..) -> (N,T,..)  # noqa: E731
        return SampleBatch(
            {
                sb.OBS: tm(buf["obs"]),
                sb.ACTIONS: tm(buf["act"]),
                sb.REWARDS: tm(rew),
                sb.TERMINATEDS: tm(done),
                sb.LOGP: tm(buf["logp"]),
                "bootstrap_value": np.asarray(boot),
            }
        )

    def set_epsilon(self, eps: float) -> bool:
        """ε-greedy override used by DQN runners. The wrapper is jitted ONCE
        with ε as a traced argument — per-iteration ε decay is free."""
        import jax
        import jax.numpy as jnp

        if self._eps_fn is None:
            base = self.module.sample_action
            act_dim = self.module.act_dim

            def eps_greedy(params, obs, rng, eps):
                action, logp, value = base(params, obs, rng)
                k1, k2 = jax.random.split(jax.random.fold_in(rng, 7))
                rand_a = jax.random.randint(k1, action.shape, 0, act_dim)
                explore = jax.random.uniform(k2, action.shape) < eps
                return jnp.where(explore, rand_a, action), logp, value

            self._eps_fn = jax.jit(eps_greedy)
        self._eps = float(eps)
        return True

    def episode_stats(self, clear: bool = True) -> dict:
        eps = self._completed
        if clear:
            self._completed = []
        if not eps:
            return {"episodes": 0, "episode_return_mean": None, "episode_len_mean": None}
        rets = [r for r, _ in eps]
        lens = [l for _, l in eps]
        return {
            "episodes": len(eps),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
            "episode_return_min": float(np.min(rets)),
            "episode_len_mean": float(np.mean(lens)),
        }

    def eval_return(
        self, params=None, episodes: int = 1, max_steps: int = 5000
    ) -> dict:
        """Roll COMPLETE episodes with the (optionally supplied) weights and
        report their mean return — the evaluation primitive evolution
        strategies are built on (reference: ``rllib/algorithms/es/``
        ``Worker.do_rollouts``). Consumes and clears the episode-stat
        buffer; ``max_steps`` bounds runaway non-terminating policies."""
        if params is not None:
            self.set_weights(params)
        # fresh episodes ONLY: without a reset, the first "episode" counted
        # here started under the PREVIOUS weights (back-to-back perturbation
        # evals on one runner would cross-contaminate the ES ranking)
        self._obs = self._obs_transform(self.vec.reset())
        self._ep_ret[:] = 0
        self._ep_len[:] = 0
        self.episode_stats(clear=True)
        chunk = max(1, min(self.fragment, 100))
        steps = 0
        while steps < max_steps and len(self._completed) < episodes:
            self._rollout(chunk)
            steps += chunk * self.vec.n
        s = self.episode_stats(clear=True)
        if s["episodes"]:
            ret = s["episode_return_mean"]
        else:
            # no episode finished within max_steps (non-terminating policy):
            # report the PARTIAL accumulated return — a literal 0.0 would
            # outrank every genuine direction in negative-reward envs
            ret = float(np.mean(self._ep_ret))
        return {"episodes": s["episodes"], "return_mean": ret, "steps": steps}

    def ping(self) -> bool:
        return True
