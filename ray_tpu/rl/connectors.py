"""Connectors: observation/action transform pipelines between env and module.

Reference: ``rllib/connectors/`` — env-to-module pipelines transform raw
observations before the policy sees them; module-to-env pipelines transform
policy outputs before the env steps them. TPU-first shape: connectors are
pure numpy on the (vectorized) host path — the jitted policy stays
transform-free so swapping connectors never recompiles it.

EnvRunner stores the TRANSFORMED observations in its sample batches, so the
learner trains on exactly what the policy consumed.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


class Connector:
    """One transform stage. Subclasses override __call__; stateful stages
    (running normalizers) expose get_state/set_state for cross-runner sync."""

    def __call__(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, data: np.ndarray) -> np.ndarray:
        """Apply WITHOUT updating internal statistics (stateless stages:
        same as __call__). Used for bootstrap/terminal observations that
        duplicate already-counted data."""
        return self(data)

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    """Compose stages left-to-right (reference: ConnectorPipelineV2)."""

    def __init__(self, stages: list[Connector]):
        self.stages = list(stages)

    def __call__(self, data):
        for s in self.stages:
            data = s(data)
        return data

    def transform(self, data):
        for s in self.stages:
            data = s.transform(data)
        return data

    def observe_dones(self, done) -> None:
        for s in self.stages:
            fn = getattr(s, "observe_dones", None)
            if fn is not None:
                fn(done)

    def transform_final(self, data):
        """Transform a transition's true NEXT_OBS: stateless stages use
        transform; stateful frame stages use their non-mutating ``peek``
        (the stack the slot would have) — call before the post-step
        __call__/observe_dones."""
        for s in self.stages:
            peek = getattr(s, "peek", None)
            data = peek(data) if peek is not None else s.transform(data)
        return data

    def get_state(self) -> dict:
        return {i: s.get_state() for i, s in enumerate(self.stages)}

    def set_state(self, state: dict) -> None:
        for i, s in enumerate(self.stages):
            if i in state:
                s.set_state(state[i])


# -- env -> module ----------------------------------------------------------


class FlattenObservations(Connector):
    """(N, *obs_shape) -> (N, prod(obs_shape)) (reference:
    connectors/env_to_module/flatten_observations.py)."""

    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class ClipObservations(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


class NormalizeObservations(Connector):
    """Running mean/var normalization (reference:
    connectors/env_to_module/mean_std_filter.py). Stats update on every
    call; get_state/set_state let an algorithm sync runners periodically."""

    def __init__(self, epsilon: float = 1e-8, clip: Optional[float] = 10.0):
        self.eps = epsilon
        self.clip = clip
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.zeros(obs.shape[1:], np.float64)
        # Chan et al. parallel update with the incoming minibatch
        bn = float(obs.shape[0])
        bmean = obs.mean(axis=0)
        bvar = obs.var(axis=0)
        delta = bmean - self._mean
        total = self._count + bn
        self._mean = self._mean + delta * (bn / total)
        self._m2 = self._m2 + bvar * bn + (delta**2) * self._count * bn / total
        self._count = total
        return self.transform(obs)

    def transform(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            return obs.astype(np.float32)
        var = self._m2 / max(self._count, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


# -- frame pipeline (Atari-style pixel preprocessing) ------------------------


class GrayscaleObservations(Connector):
    """(N, H, W, 3) RGB → (N, H, W) luma (reference: the Atari wrapper
    stack's grayscale stage; ITU-R 601 weights)."""

    _W = np.array([0.299, 0.587, 0.114], np.float32)

    def __call__(self, obs):
        return self.transform(obs)

    def transform(self, obs):
        obs = np.asarray(obs, np.float32)
        return obs @ self._W


class ResizeObservations(Connector):
    """Nearest-neighbor spatial resize of (N, H, W[, C]) frames — pure
    numpy (no cv2 in the image), exact enough for RL preprocessing."""

    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, obs):
        return self.transform(obs)

    def transform(self, obs):
        obs = np.asarray(obs)
        H, W = obs.shape[1], obs.shape[2]
        rows = (np.arange(self.h) * H // self.h).clip(0, H - 1)
        cols = (np.arange(self.w) * W // self.w).clip(0, W - 1)
        return obs[:, rows][:, :, cols]


class ScaleObservations(Connector):
    """uint8 pixels → [0, 1] floats."""

    def __init__(self, scale: float = 1.0 / 255.0):
        self.scale = scale

    def __call__(self, obs):
        return self.transform(obs)

    def transform(self, obs):
        return np.asarray(obs, np.float32) * self.scale


class FrameStack(Connector):
    """Stack the last k frames per env slot along a trailing channel axis
    (reference: the Atari frame-stack wrapper, done connector-side so the
    module sees (N, H, W, k)).

    Stateful: the env runner notifies episode ends via ``observe_dones`` so
    a fresh episode's stack starts from its reset frame (replicated), never
    mixing frames across episodes. ``transform`` (the stateless path, used
    for shape probes and truncation-bootstrap observations) replicates the
    single frame k times — exact at episode starts, an approximation
    elsewhere (termination-style envs never consume it).
    """

    def __init__(self, k: int = 4):
        self.k = k
        self._stacks: Optional[np.ndarray] = None  # (N, H, W, k*C)
        self._c = 1  # channels per FRAME: the slide drops/appends C at a time
        self._pending_reset: Optional[np.ndarray] = None  # bool (N,)

    @staticmethod
    def _frames_of(obs):
        obs = np.asarray(obs, np.float32)
        return obs[..., None] if obs.ndim == 3 else obs  # (N, H, W) → (N,H,W,1)

    def _replicate(self, frames):
        # per-FRAME blocks, not interleaved channels: [f, f, ..., f]
        return np.concatenate([frames] * self.k, axis=-1)

    def __call__(self, obs):
        frames = self._frames_of(obs)
        n = frames.shape[0]
        self._c = frames.shape[-1]
        if self._stacks is None or len(self._stacks) != n:
            self._stacks = self._replicate(frames)
        else:
            if self._pending_reset is not None and self._pending_reset.any():
                idx = np.nonzero(self._pending_reset)[0]
                self._stacks[idx] = self._replicate(frames[idx])
                keep = ~self._pending_reset
            else:
                keep = np.ones(n, bool)
            idx = np.nonzero(keep)[0]
            if len(idx):
                self._stacks[idx] = np.concatenate(
                    [self._stacks[idx][..., self._c :], frames[idx]], axis=-1
                )
        self._pending_reset = None
        return self._stacks.copy()

    def observe_dones(self, done: np.ndarray) -> None:
        """Called by the env runner right after stepping: the NEXT observed
        frame for these slots is a reset frame — restart their stacks."""
        self._pending_reset = np.asarray(done, bool)

    def peek(self, obs):
        """The stack each slot WOULD have after observing ``obs``, without
        mutating state — used for a transition's true NEXT_OBS (the
        ``final`` buffer): current frames slid by one, new frame appended.
        Must be called BEFORE the post-step __call__ updates the stacks."""
        frames = self._frames_of(obs)
        if self._stacks is None or len(self._stacks) != frames.shape[0]:
            return self._replicate(frames)
        return np.concatenate([self._stacks[..., frames.shape[-1] :], frames], axis=-1)

    def transform(self, obs):
        return self._replicate(self._frames_of(obs))

    def get_state(self) -> dict:
        # per-env stacks are RUNNER-LOCAL episode state: syncing them into
        # a restarted runner would slide another runner's frames into its
        # fresh episodes (cross-episode mixing). Nothing to share.
        return {}

    def set_state(self, state: dict) -> None:
        pass


# -- module -> env ----------------------------------------------------------


class ClipActions(Connector):
    """Clip continuous actions into the env's Box bounds (reference:
    connectors/module_to_env ClipActions)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class GaussianActionNoise(Connector):
    """Additive exploration noise for deterministic policies (TD3/DDPG)."""

    def __init__(self, scale: float, low=None, high=None, seed: Optional[int] = None):
        self.scale = scale
        self.low, self.high = low, high
        self._rng = np.random.default_rng(seed)

    def __call__(self, actions):
        out = np.asarray(actions) + self._rng.normal(0.0, self.scale, np.shape(actions))
        if self.low is not None:
            out = np.clip(out, self.low, self.high)
        return out.astype(np.float32)
