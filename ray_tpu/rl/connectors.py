"""Connectors: observation/action transform pipelines between env and module.

Reference: ``rllib/connectors/`` — env-to-module pipelines transform raw
observations before the policy sees them; module-to-env pipelines transform
policy outputs before the env steps them. TPU-first shape: connectors are
pure numpy on the (vectorized) host path — the jitted policy stays
transform-free so swapping connectors never recompiles it.

EnvRunner stores the TRANSFORMED observations in its sample batches, so the
learner trains on exactly what the policy consumed.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


class Connector:
    """One transform stage. Subclasses override __call__; stateful stages
    (running normalizers) expose get_state/set_state for cross-runner sync."""

    def __call__(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, data: np.ndarray) -> np.ndarray:
        """Apply WITHOUT updating internal statistics (stateless stages:
        same as __call__). Used for bootstrap/terminal observations that
        duplicate already-counted data."""
        return self(data)

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    """Compose stages left-to-right (reference: ConnectorPipelineV2)."""

    def __init__(self, stages: list[Connector]):
        self.stages = list(stages)

    def __call__(self, data):
        for s in self.stages:
            data = s(data)
        return data

    def transform(self, data):
        for s in self.stages:
            data = s.transform(data)
        return data

    def get_state(self) -> dict:
        return {i: s.get_state() for i, s in enumerate(self.stages)}

    def set_state(self, state: dict) -> None:
        for i, s in enumerate(self.stages):
            if i in state:
                s.set_state(state[i])


# -- env -> module ----------------------------------------------------------


class FlattenObservations(Connector):
    """(N, *obs_shape) -> (N, prod(obs_shape)) (reference:
    connectors/env_to_module/flatten_observations.py)."""

    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class ClipObservations(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


class NormalizeObservations(Connector):
    """Running mean/var normalization (reference:
    connectors/env_to_module/mean_std_filter.py). Stats update on every
    call; get_state/set_state let an algorithm sync runners periodically."""

    def __init__(self, epsilon: float = 1e-8, clip: Optional[float] = 10.0):
        self.eps = epsilon
        self.clip = clip
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.zeros(obs.shape[1:], np.float64)
        # Chan et al. parallel update with the incoming minibatch
        bn = float(obs.shape[0])
        bmean = obs.mean(axis=0)
        bvar = obs.var(axis=0)
        delta = bmean - self._mean
        total = self._count + bn
        self._mean = self._mean + delta * (bn / total)
        self._m2 = self._m2 + bvar * bn + (delta**2) * self._count * bn / total
        self._count = total
        return self.transform(obs)

    def transform(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            return obs.astype(np.float32)
        var = self._m2 / max(self._count, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        return out.astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


# -- module -> env ----------------------------------------------------------


class ClipActions(Connector):
    """Clip continuous actions into the env's Box bounds (reference:
    connectors/module_to_env ClipActions)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class GaussianActionNoise(Connector):
    """Additive exploration noise for deterministic policies (TD3/DDPG)."""

    def __init__(self, scale: float, low=None, high=None, seed: Optional[int] = None):
        self.scale = scale
        self.low, self.high = low, high
        self._rng = np.random.default_rng(seed)

    def __call__(self, actions):
        out = np.asarray(actions) + self._rng.normal(0.0, self.scale, np.shape(actions))
        if self.low is not None:
            out = np.clip(out, self.low, self.high)
        return out.astype(np.float32)
