"""Observation/action spaces (gymnasium-compatible subset).

The reference consumes gymnasium spaces throughout RLlib; this image ships
no gym, so ray_tpu.rl defines the two spaces its algorithms need with the
same attribute surface (``shape``, ``dtype``, ``n``, ``low``, ``high``,
``sample``, ``contains``) so user envs written against gymnasium drop in.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class Space:
    shape: tuple
    dtype: np.dtype

    def sample(self, rng: Optional[np.random.Generator] = None):
        raise NotImplementedError

    def contains(self, x) -> bool:
        raise NotImplementedError


class Discrete(Space):
    def __init__(self, n: int):
        self.n = int(n)
        self.shape = ()
        self.dtype = np.int64

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(0, self.n))

    def contains(self, x) -> bool:
        return 0 <= int(x) < self.n

    def __repr__(self):
        return f"Discrete({self.n})"

    def __eq__(self, other):
        return isinstance(other, Discrete) and other.n == self.n


class Box(Space):
    def __init__(self, low, high, shape: Optional[Sequence[int]] = None, dtype=np.float32):
        if shape is None:
            shape = np.broadcast(np.asarray(low), np.asarray(high)).shape
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.low = np.broadcast_to(np.asarray(low, self.dtype), self.shape).copy()
        self.high = np.broadcast_to(np.asarray(high, self.dtype), self.shape).copy()

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        lo = np.where(np.isfinite(self.low), self.low, -1.0)
        hi = np.where(np.isfinite(self.high), self.high, 1.0)
        return rng.uniform(lo, hi, size=self.shape).astype(self.dtype)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return x.shape == self.shape and bool(np.all(x >= self.low - 1e-6)) and bool(
            np.all(x <= self.high + 1e-6)
        )

    def __repr__(self):
        return f"Box{self.shape}"

    def __eq__(self, other):
        return (
            isinstance(other, Box)
            and other.shape == self.shape
            and np.allclose(other.low, self.low)
            and np.allclose(other.high, self.high)
        )
