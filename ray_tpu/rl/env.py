"""Environments: gymnasium-API base class, classic-control built-ins,
vectorization, and a registry.

Reference: RLlib consumes external gym envs (``rllib/env/``); this image has
no gym, so the classic-control dynamics used by the reference's smoke/learning
tests (CartPole for PPO/DQN/IMPALA, Pendulum for continuous control) are
implemented natively with the same physics constants as gymnasium's
``cartpole.py`` / ``pendulum.py`` public formulas. API:
``reset(seed) -> (obs, info)``, ``step(a) -> (obs, r, terminated, truncated,
info)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.rl.spaces import Box, Discrete, Space


class Env:
    observation_space: Space
    action_space: Space
    spec_max_episode_steps: Optional[int] = None

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError

    def close(self):
        pass


class CartPoleEnv(Env):
    """Pole balancing; reward 1 per step; terminates past ±12° / ±2.4m."""

    def __init__(self, max_episode_steps: int = 500):
        self.observation_space = Box(-np.inf, np.inf, shape=(4,))
        self.action_space = Discrete(2)
        self.spec_max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng()
        self._state = None
        self._t = 0

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32), {}

    def step(self, action):
        x, x_dot, th, th_dot = self._state
        force = 10.0 if action == 1 else -10.0
        costh, sinth = np.cos(th), np.sin(th)
        # Standard cart-pole dynamics (masscart 1.0, masspole 0.1, len 0.5).
        temp = (force + 0.05 * th_dot**2 * sinth) / 1.1
        th_acc = (9.8 * sinth - costh * temp) / (0.5 * (4.0 / 3.0 - 0.1 * costh**2 / 1.1))
        x_acc = temp - 0.05 * th_acc * costh / 1.1
        tau = 0.02
        x, x_dot = x + tau * x_dot, x_dot + tau * x_acc
        th, th_dot = th + tau * th_dot, th_dot + tau * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._t += 1
        terminated = bool(abs(x) > 2.4 or abs(th) > 12 * np.pi / 180)
        truncated = self._t >= self.spec_max_episode_steps
        return self._state.astype(np.float32), 1.0, terminated, truncated, {}


class PendulumEnv(Env):
    """Continuous control: swing up; reward = -(angle² + .1ω² + .001u²)."""

    def __init__(self, max_episode_steps: int = 200):
        self.observation_space = Box(-np.inf, np.inf, shape=(3,))
        self.action_space = Box(-2.0, 2.0, shape=(1,))
        self.spec_max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng()
        self._th = self._thdot = 0.0
        self._t = 0

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs(), {}

    def _obs(self):
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot], dtype=np.float32)

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        reward = -(norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2)
        thdot = thdot + (3 * 9.81 / 2 * np.sin(th) + 3.0 * u) * 0.05
        thdot = float(np.clip(thdot, -8.0, 8.0))
        th = th + thdot * 0.05
        self._th, self._thdot = th, thdot
        self._t += 1
        return self._obs(), float(reward), False, self._t >= self.spec_max_episode_steps, {}


class GridWorldEnv(Env):
    """Tiny deterministic 1-D corridor (debug env; reference uses similar
    toy envs for unit tests)."""

    def __init__(self, n: int = 8):
        self.n = n
        self.observation_space = Box(0.0, float(n), shape=(1,))
        self.action_space = Discrete(2)
        self.spec_max_episode_steps = 4 * n
        self._pos = 0
        self._t = 0

    def reset(self, *, seed=None):
        self._pos, self._t = 0, 0
        return np.array([0.0], dtype=np.float32), {}

    def step(self, action):
        self._pos = max(0, min(self.n - 1, self._pos + (1 if action == 1 else -1)))
        self._t += 1
        done = self._pos == self.n - 1
        return (
            np.array([float(self._pos)], dtype=np.float32),
            1.0 if done else -0.01,
            done,
            self._t >= self.spec_max_episode_steps,
            {},
        )


class CatchPixelEnv(Env):
    """Atari-class pixel control without ALE (not installable here): the
    classic DeepMind "Catch" game rendered as 84x84x3 uint8 RGB frames —
    the agent sees raw pixels and must drive the frame-connector pipeline
    (grayscale → resize → scale → frame-stack) exactly like a Pong setup.

    A ball falls from a random top column; a 3-pixel paddle at the bottom
    moves {left, stay, right}; reward +1 on catch, -1 on miss; an episode is
    ``balls`` consecutive drops (score range [-balls, +balls]). Random play
    averages ≈ -0.6·balls; a solved policy ≈ +balls.
    """

    SIZE = 21  # logical grid; rendered 4x → 84x84
    SCALE = 4

    def __init__(self, balls: int = 3):
        px = self.SIZE * self.SCALE
        self.observation_space = Box(0, 255, shape=(px, px, 3))
        self.action_space = Discrete(3)
        self.spec_max_episode_steps = balls * self.SIZE + 1
        self.balls = balls
        self._rng = np.random.default_rng()
        self._t = 0

    def _render(self) -> np.ndarray:
        g = np.zeros((self.SIZE, self.SIZE, 3), np.uint8)
        g[self._ball_r, self._ball_c] = (255, 255, 255)
        lo = max(self._paddle - 1, 0)
        hi = min(self._paddle + 1, self.SIZE - 1)
        g[self.SIZE - 1, lo : hi + 1] = (0, 255, 0)
        return np.repeat(np.repeat(g, self.SCALE, 0), self.SCALE, 1)

    def _drop(self):
        self._ball_r = 0
        self._ball_c = int(self._rng.integers(0, self.SIZE))

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._paddle = self.SIZE // 2
        self._caught = 0
        self._t = 0
        self._drop()
        return self._render(), {}

    def step(self, action):
        self._paddle = int(np.clip(self._paddle + (int(action) - 1), 1, self.SIZE - 2))
        self._ball_r += 1
        self._t += 1
        reward = 0.0
        done = False
        if self._ball_r >= self.SIZE - 1:
            reward = 1.0 if abs(self._ball_c - self._paddle) <= 1 else -1.0
            self._caught += 1
            if self._caught >= self.balls:
                done = True
            else:
                self._drop()
        return (
            self._render(),
            reward,
            done,
            self._t >= self.spec_max_episode_steps,
            {},
        )


class MinAtarBreakoutEnv(Env):
    """MinAtar-style Breakout: 10x10 grid, 4 boolean channels (paddle,
    ball, ball-trail, bricks) — the miniaturized Atari family the
    reference's release learning tests graduate to (MinAtar is the public
    CPU-scale analog of the 30-60-min Atari criteria,
    ``release/rllib_tests/README.rst``). Dynamics follow the published
    MinAtar breakout rules: three brick rows, diagonal ball, paddle at the
    bottom row, +1 per brick, wall clears re-spawn, episode ends when the
    ball passes the paddle. Random play measures 0.14 mean return
    (200 episodes, seed 0) — the learning tests' baseline.
    """

    SIZE = 10

    def __init__(self, max_episode_steps: int = 400):
        self.observation_space = Box(0.0, 1.0, shape=(self.SIZE, self.SIZE, 4))
        self.action_space = Discrete(3)  # left, stay, right
        self.spec_max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng()
        self._t = 0

    def _spawn_ball(self):
        side = int(self._rng.integers(0, 2))
        self._ball = [3, 0 if side == 0 else self.SIZE - 1]
        self._dy, self._dx = 1, (1 if side == 0 else -1)
        self._last = list(self._ball)

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._paddle = self.SIZE // 2
        self._bricks = np.zeros((self.SIZE, self.SIZE), bool)
        self._bricks[1:4, :] = True
        self._spawn_ball()
        self._t = 0
        return self._obs(), {}

    def _obs(self):
        g = np.zeros((self.SIZE, self.SIZE, 4), np.float32)
        g[self.SIZE - 1, self._paddle, 0] = 1.0
        g[self._ball[0], self._ball[1], 1] = 1.0
        g[self._last[0], self._last[1], 2] = 1.0
        g[:, :, 3] = self._bricks
        return g

    def step(self, action):
        self._paddle = int(np.clip(self._paddle + (int(action) - 1), 0, self.SIZE - 1))
        self._t += 1
        reward = 0.0
        terminated = False
        self._last = list(self._ball)
        ny, nx = self._ball[0] + self._dy, self._ball[1] + self._dx
        if nx < 0 or nx >= self.SIZE:  # side wall
            self._dx = -self._dx
            nx = self._ball[1] + self._dx
        if ny < 0:  # ceiling
            self._dy = 1
            ny = self._ball[0] + self._dy
        if 0 <= ny < self.SIZE and self._bricks[ny, nx]:
            self._bricks[ny, nx] = False
            reward = 1.0
            self._dy = -self._dy
            ny = self._ball[0] + self._dy
            ny = max(min(ny, self.SIZE - 1), 0)
        if ny == self.SIZE - 1:  # paddle row
            if nx == self._paddle:
                self._dy = -1
                ny = self._ball[0] - 1
            else:
                terminated = True
        if not self._bricks.any():
            self._bricks[1:4, :] = True  # wall cleared: respawn
        self._ball = [int(ny), int(nx)]
        truncated = self._t >= self.spec_max_episode_steps
        return self._obs(), reward, terminated, truncated, {}


_REGISTRY: dict[str, Callable[[], Env]] = {
    "CartPole-v1": CartPoleEnv,
    "Pendulum-v1": PendulumEnv,
    "GridWorld-v0": GridWorldEnv,
    "CatchPixel-v0": CatchPixelEnv,
    "MinAtarBreakout-v0": MinAtarBreakoutEnv,
}


def register_env(name: str, creator: Callable[[], Env]) -> None:
    """Reference: ``ray.tune.registry.register_env``."""
    _REGISTRY[name] = creator


def make_vector_env(spec, n_envs: int, seed: Optional[int] = None):
    """SyncVectorEnv for single-agent envs; MultiAgentVectorEnv (same
    interface, slots = env x agent) when the creator builds a MultiAgentEnv
    — shared-policy multi-agent training with unchanged algorithms."""
    from ray_tpu.rl.multi_agent import MultiAgentEnv, MultiAgentVectorEnv

    probe = make_env(spec)
    if isinstance(probe, MultiAgentEnv):
        return MultiAgentVectorEnv(spec, n_envs, seed=seed)
    return SyncVectorEnv(spec, n_envs, seed=seed, _first=probe)


def make_env(spec) -> Env:
    if isinstance(spec, Env):
        return spec
    if isinstance(spec, str):
        if spec not in _REGISTRY:
            raise KeyError(f"Unknown env {spec!r}; registered: {sorted(_REGISTRY)}")
        return _REGISTRY[spec]()
    if callable(spec):
        return spec()
    raise TypeError(f"Cannot build env from {spec!r}")


class SyncVectorEnv:
    """N envs stepped in lockstep with auto-reset (reference:
    ``rllib/env/vector_env.py``). Obs/rewards/dones are stacked numpy arrays
    ready for one batched policy forward — the policy runs ONE jitted call
    per vector step regardless of N."""

    def __init__(self, creator: Callable[[], Env], n: int, seed: Optional[int] = None, _first=None):
        self.envs = ([_first] if _first is not None else []) + [
            make_env(creator) for _ in range(n - (1 if _first is not None else 0))
        ]
        self.n = n
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._seed = seed

    def reset(self):
        obs = []
        for i, e in enumerate(self.envs):
            o, _ = e.reset(seed=None if self._seed is None else self._seed + i)
            obs.append(o)
        return np.stack(obs)

    def step(self, actions):
        """Returns ``(obs, rewards, terminateds, truncateds, final_obs)``.

        ``obs`` is the post-auto-reset observation (what the policy acts on
        next); ``final_obs`` is the TRUE next observation of the transition —
        the pre-reset terminal obs for done envs (gymnasium's
        ``final_observation`` info field). Off-policy algorithms must
        bootstrap from ``final_obs``, never from a reset state.
        """
        obs, rews, terms, truncs, finals = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, _info = e.step(a)
            final = o
            if term or trunc:
                o, _ = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
            finals.append(final)
        return (
            np.stack(obs),
            np.asarray(rews, np.float32),
            np.asarray(terms, bool),
            np.asarray(truncs, bool),
            np.stack(finals),
        )
