// ray_tpu dashboard app. Hand-written vanilla JS over the /api/* REST
// surface (reference counterpart: dashboard/client/src React app). Views:
// overview tiles + resource meters, filterable entity tables, a chrome-trace
// timeline renderer, a collapsed-stack flamegraph viewer, and a job log tail.
"use strict";

const TABS = ["nodes", "actors", "tasks", "objects", "placement_groups",
              "jobs", "timeline", "flamegraph", "metrics", "worker_stacks"];
let tab = "nodes";
let filterState = "";   // state filter for tasks/actors
let filterText = "";    // substring filter
let logJob = null;      // selected job for the log tail
let flameData = null;   // last fetched profile
let profileBusy = false;

const esc = s => String(s).replace(/[&<>]/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
const fmt = v => v === undefined || v === null ? "<span class=muted>—</span>" :
  typeof v === "object" ? "<code>" + esc(JSON.stringify(v)) + "</code>" : esc(v);
async function j(u) { const r = await fetch(u); if (!r.ok) throw new Error(u + ": " + r.status); return r.json(); }

const STATE_COLOR = {ALIVE:"var(--good)", RUNNING:"var(--accent)", PENDING:"var(--warn)",
  RESTARTING:"var(--warn)", DEAD:"var(--bad)", FAILED:"var(--bad)", FINISHED:"var(--ink2)",
  WAITING_DEPS:"var(--warn)", ASSIGNED:"var(--accent)", SUCCEEDED:"var(--good)"};
const stateCell = s => `<span class=st><i style="background:${STATE_COLOR[s]||"var(--ink2)"}"></i>${esc(s)}</span>`;

function applyFilters(rows, stateCol) {
  let out = rows;
  if (filterState && stateCol) out = out.filter(r => r[stateCol] === filterState);
  if (filterText) {
    const q = filterText.toLowerCase();
    out = out.filter(r => JSON.stringify(r).toLowerCase().includes(q));
  }
  return out;
}

function table(rows, cols, stateCol) {
  if (!rows || !rows.length) return "<p class=muted>none</p>";
  const shown = applyFilters(rows, stateCol);
  let h = "<table><tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") + "</tr>";
  for (const r of shown.slice(0, 500))
    h += "<tr>" + cols.map(c => `<td>${c === stateCol ? stateCell(r[c]) : fmt(r[c])}</td>`).join("") + "</tr>";
  h += "</table>";
  if (shown.length > 500) h += `<p class=muted>…and ${shown.length - 500} more</p>`;
  if (shown.length !== rows.length) h += `<p class=muted>${shown.length} of ${rows.length} shown (filtered)</p>`;
  return h;
}

function meters(res) {
  const tot = res.total || {}, avail = res.available || {};
  return Object.keys(tot).filter(k => k !== "memory").sort().map(k => {
    const t = tot[k], u = t - (avail[k] ?? t), pct = t ? Math.round(100 * u / t) : 0;
    return `<div class=meter><span class=lbl><span>${esc(k)}</span><span>${+u.toFixed(2)} / ${+t.toFixed(2)} used</span></span>
      <span class=bar><i style="width:${pct}%"></i></span></div>`;
  }).join("");
}

const tile = (k, v) => `<div class=tile><div class=v>${v}</div><div class=k>${esc(k)}</div></div>`;

// ---------------------------------------------------------------- toolbar
function toolbar() {
  if (tab === "tasks" || tab === "actors") {
    const states = tab === "tasks"
      ? ["", "PENDING", "WAITING_DEPS", "ASSIGNED", "RUNNING", "FINISHED", "FAILED"]
      : ["", "PENDING", "ALIVE", "RESTARTING", "DEAD"];
    return `<select id=fstate onchange="filterState=this.value;render()">` +
      states.map(s => `<option value="${s}" ${s === filterState ? "selected" : ""}>${s || "all states"}</option>`).join("") +
      `</select><input id=ftext placeholder="filter…" value="${esc(filterText)}"
        oninput="filterText=this.value;renderView()">`;
  }
  if (tab === "flamegraph")
    return `<button onclick="profileNow()" ${profileBusy ? "disabled" : ""}>
      ${profileBusy ? "profiling…" : "profile workers (2s)"}</button>
      <span class=sub>sampling CPU profile of every live worker</span>`;
  if (tab === "timeline")
    return `<span class=sub>task spans from the event feed; also exportable:
      <code>ray_tpu timeline</code> → chrome://tracing</span>`;
  return "";
}

// --------------------------------------------------------------- timeline
function renderTimeline(events) {
  if (!events.length) return "<p class=muted>no finished task spans yet</p>";
  const t0 = Math.min(...events.map(e => e.ts));
  const t1 = Math.max(...events.map(e => e.ts + e.dur));
  const span = Math.max(t1 - t0, 1);
  const lanes = [...new Set(events.map(e => e.tid))];
  const laneOf = Object.fromEntries(lanes.map((l, i) => [l, i]));
  const W = 1100, ROW = 18, H = Math.min(lanes.length, 60) * ROW + 30;
  const cnv = document.createElement("canvas");
  cnv.width = W * devicePixelRatio; cnv.height = H * devicePixelRatio;
  cnv.style.height = H + "px";
  const ctx = cnv.getContext("2d");
  ctx.scale(devicePixelRatio, devicePixelRatio);
  const css = getComputedStyle(document.body);
  const colors = {task: css.getPropertyValue("--accent"), actor_method: css.getPropertyValue("--good"),
                  actor_create: css.getPropertyValue("--warn")};
  for (const e of events) {
    const lane = laneOf[e.tid]; if (lane >= 60) continue;
    const x = 40 + (e.ts - t0) / span * (W - 50);
    const w = Math.max(e.dur / span * (W - 50), 1.5);
    ctx.fillStyle = (colors[e.cat] || css.getPropertyValue("--ink2")).trim();
    ctx.fillRect(x, 24 + lane * ROW, w, ROW - 4);
  }
  ctx.fillStyle = css.getPropertyValue("--ink2").trim();
  ctx.font = "11px system-ui";
  ctx.fillText(`${events.length} spans · ${(span / 1e6).toFixed(2)}s window · one row per task chain` +
    (lanes.length > 60 ? ` · first 60/${lanes.length} rows` : ""), 40, 14);
  const wrap = document.createElement("div"); wrap.id = "timeline"; wrap.appendChild(cnv);
  return wrap;
}

// -------------------------------------------------------------- flamegraph
// Input: collapsed stack lines "frameA;frameB;frameC <count>" merged over
// all workers; output: an SVG flame graph (depth-stacked, width ∝ samples).
function buildFlame(collapsedTexts) {
  const root = {name: "all", value: 0, children: new Map()};
  for (const text of collapsedTexts) {
    for (const line of text.split("\n")) {
      const sp = line.lastIndexOf(" ");
      if (sp <= 0) continue;
      const count = parseInt(line.slice(sp + 1), 10);
      if (!count) continue;
      const frames = line.slice(0, sp).split(";");
      let node = root; root.value += count;
      for (const f of frames) {
        if (!node.children.has(f)) node.children.set(f, {name: f, value: 0, children: new Map()});
        node = node.children.get(f);
        node.value += count;
      }
    }
  }
  return root;
}

function flameSVG(root) {
  if (!root.value) return "<p class=muted>no samples (workers idle?)</p>";
  const W = 1100, ROW = 17;
  const palette = ["#e05c5c", "#e08f4f", "#e0c24f", "#9fc45c", "#5cb8a6", "#5c95d6", "#9a7fd6"];
  let maxDepth = 0, rects = [];
  (function walk(node, x, depth, w) {
    maxDepth = Math.max(maxDepth, depth);
    if (w < 1) return;
    if (depth > 0) {
      const color = palette[(node.name.length + depth) % palette.length];
      const label = w > 40 ? esc(node.name.slice(0, Math.floor(w / 6.2))) : "";
      rects.push(`<g><rect x="${x.toFixed(1)}" y="${depth * ROW}" width="${w.toFixed(1)}" height="${ROW - 1}" fill="${color}">
        <title>${esc(node.name)} — ${node.value} samples (${(100 * node.value / root.value).toFixed(1)}%)</title></rect>
        <text x="${(x + 3).toFixed(1)}" y="${depth * ROW + 12}">${label}</text></g>`);
    }
    let cx = x;
    const kids = [...node.children.values()].sort((a, b) => b.value - a.value);
    for (const k of kids) {
      const kw = w * k.value / node.value;
      walk(k, cx, depth + 1, kw);
      cx += kw;
    }
  })(root, 0, 0, W);
  const H = (maxDepth + 1) * ROW;
  return `<div id=flame><svg viewBox="0 0 ${W} ${H}" height="${H}">${rects.join("")}</svg>
    <p class=sub>${root.value} samples · width ∝ CPU time · hover for frame detail</p></div>`;
}

async function profileNow() {
  profileBusy = true; render();
  try { flameData = await j("/api/profile?seconds=2"); }
  catch (e) { flameData = {error: String(e)}; }
  profileBusy = false; render();
}

// -------------------------------------------------------------------- logs
async function logsView() {
  let jobs = [];
  try { jobs = await j("/api/jobs"); } catch (e) { /* job API optional */ }
  let h = table(jobs, ["job_id", "status", "entrypoint"], "status");
  if (jobs.length) {
    if (logJob === null) logJob = jobs[0].job_id;
    h += `<p><select onchange="logJob=this.value;render()">` +
      jobs.map(x => `<option value="${esc(x.job_id)}" ${x.job_id === logJob ? "selected" : ""}>${esc(x.job_id)}</option>`).join("") +
      `</select> <span class=sub>log tail (auto-refreshes)</span></p>`;
    try {
      const lg = await j("/api/logs?job_id=" + encodeURIComponent(logJob));
      h += `<pre class=loglines>${esc(lg.logs || "(empty)")}</pre>`;
    } catch (e) { h += `<p class=muted>${esc(e)}</p>`; }
  } else {
    h += "<p class=muted>no jobs submitted — job logs appear here " +
         "(<code>ray_tpu submit ...</code>)</p>";
  }
  return h;
}

// -------------------------------------------------------------------- main
async function view(t, pre) {
  if (t === "nodes") return table(pre.nodes, ["NodeID", "Alive", "Resources", "Available", "Labels"], "");
  if (t === "actors") return table(pre.actors, ["actor_id", "class_name", "name", "state", "node_id"], "state");
  if (t === "tasks") return table(await j("/api/tasks"), ["task_id", "name", "state", "kind", "node_id"], "state");
  if (t === "objects") return table(await j("/api/objects"), ["object_id", "size", "where", "refcount", "pins"], "");
  if (t === "placement_groups") return table(await j("/api/placement_groups"), ["pg_id", "state", "strategy", "bundles"], "state");
  if (t === "jobs") return logsView();
  if (t === "timeline") return renderTimeline(await j("/api/timeline"));
  if (t === "flamegraph") {
    if (!flameData) return "<p class=muted>press “profile workers” to sample</p>";
    if (flameData.error) return `<p class=muted>${esc(flameData.error)}</p>`;
    const texts = [];
    for (const per of Object.values(flameData)) for (const txt of Object.values(per)) texts.push(txt);
    return flameSVG(buildFlame(texts));
  }
  if (t === "metrics") return "<pre>" + esc(JSON.stringify(await j("/api/metrics"), null, 1)) + "</pre>" +
    '<p class=muted>prometheus text at <a href="/metrics">/metrics</a> · grafana board: <code>ray_tpu grafana</code></p>';
  if (t === "worker_stacks") {
    const s = await j("/api/worker_stacks");
    return Object.entries(s).map(([node, per]) => Object.entries(per).map(([pid, txt]) =>
      `<h3 class=muted style="font-size:.85rem">node ${esc(node).slice(0, 8)} · pid ${esc(pid)}</h3><pre>${esc(txt)}</pre>`
    ).join("")).join("") || "<p class=muted>none</p>";
  }
  return "";
}

async function renderView() {
  // re-render only #view (keeps toolbar inputs focused while typing)
  try {
    const [nodes, actors] = await Promise.all([j("/api/nodes"), j("/api/actors")]);
    const v = await view(tab, {nodes, actors});
    const el = document.getElementById("view");
    if (typeof v === "string") el.innerHTML = v;
    else { el.innerHTML = ""; el.appendChild(v); }
  } catch (e) {
    document.getElementById("view").innerHTML = "<p class=muted>" + esc(e) + "</p>";
  }
}

async function render() {
  try {
    const [res, nodes, actors, summary] = await Promise.all([
      j("/api/cluster_resources"), j("/api/nodes"), j("/api/actors"), j("/api/summary")]);
    const tasks = (summary && summary.tasks && summary.tasks.by_state) || (summary && summary.tasks) || {};
    document.getElementById("meta").textContent = new Date().toLocaleTimeString();
    document.getElementById("tiles").innerHTML =
      tile("nodes", nodes.filter(n => (n.Alive ?? n.alive) !== false).length) +
      tile("actors", actors.length) +
      tile("running tasks", tasks.RUNNING || 0) +
      tile("pending tasks", (tasks.PENDING || 0) + (tasks.WAITING_DEPS || 0)) +
      tile("objects", (summary && summary.objects && summary.objects.total) ?? "—");
    document.getElementById("meters").innerHTML = meters(res);
    document.getElementById("taskcounts").innerHTML = Object.entries(tasks)
      .map(([s, n]) => `<span>${stateCell(s)} ${n}</span>`).join("");
    document.getElementById("toolbar").innerHTML = toolbar();
    const v = await view(tab, {nodes, actors});
    const el = document.getElementById("view");
    if (typeof v === "string") el.innerHTML = v;
    else { el.innerHTML = ""; el.appendChild(v); }
  } catch (e) {
    document.getElementById("view").innerHTML = "<p class=muted>" + esc(e) + "</p>";
  }
}

document.getElementById("tabs").innerHTML = TABS.map(t =>
  `<button id="tab-${t}" onclick="tab='${t}';syncTabs();render()">${t.replace(/_/g, " ")}</button>`).join("");
function syncTabs() { for (const t of TABS) document.getElementById("tab-" + t).className = t === tab ? "on" : ""; }
syncTabs(); render();
setInterval(() => {
  if (document.getElementById("auto").checked && tab !== "flamegraph") render();
}, 3000);
