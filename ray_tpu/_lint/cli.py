"""raylint CLI. ``python -m ray_tpu.lint [paths] [options]``.

Exit codes: 0 clean, 1 violations / import problems found, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional, Sequence

from ray_tpu._lint import baseline as baseline_mod
from ray_tpu._lint.core import all_rules, display_path_for, get_rule, run_paths
from ray_tpu._lint.imports_check import check_imports


def _rule_name(rule_id: str) -> str:
    rule = get_rule(rule_id)
    return rule.name if rule is not None else ""


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.lint",
        description="AST-based distributed-correctness linter for ray_tpu.",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files or directories to lint (default: the ray_tpu package)",
    )
    p.add_argument(
        "--format", choices=("text", "json", "github"), default="text", dest="fmt",
        help="output format (github = workflow-command inline annotations)",
    )
    p.add_argument(
        "--profile", action="store_true",
        help="print wall-time per phase (parse / index / each rule) to stderr",
    )
    p.add_argument(
        "--profile-json", metavar="PATH", default=None,
        help="also write the per-phase/per-rule profile as JSON to PATH "
        "(CI uploads it as the lint artifact)",
    )
    p.add_argument(
        "--changed-only", action="store_true",
        help="report violations only for files git sees as changed "
        "(working tree vs HEAD, plus untracked, plus the merge-base diff "
        "against --changed-base when given); the whole-program index is "
        "still built over every scanned file",
    )
    p.add_argument(
        "--changed-base", metavar="REF", default=None,
        help="git ref the PR diverged from (e.g. origin/main); adds "
        "`git diff REF...HEAD` to the --changed-only file set",
    )
    p.add_argument(
        "--baseline", metavar="PATH", default=None,
        help="baseline file (default: <root>/tools/raylint-baseline.json if present)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file; report everything",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="record all current violations into the baseline file and exit 0",
    )
    p.add_argument(
        "--select", metavar="RULES", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--ignore", metavar="RULES", default=None,
        help="comma-separated rule ids to skip",
    )
    p.add_argument(
        "--check-imports", action="store_true",
        help="instead of linting, py_compile every module under the given "
        "directories and fail on module-level import cycles",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog and exit"
    )
    return p


def _default_package_path() -> str:
    # prefer the checkout we are running from
    here = Path(__file__).resolve().parent.parent
    return str(here)


def _git_changed_files(repo_root: Path, base: Optional[str]) -> Optional[set]:
    """Resolved ABSOLUTE paths of changed ``.py`` files (git reports them
    relative to its toplevel, so they are re-anchored there): working
    tree vs HEAD, untracked files, and (with ``base``) the merge-base
    diff ``base...HEAD``.  None when git cannot answer — including a
    ``--changed-base`` ref that does not resolve (shallow clone, typo'd
    ref): a PR fast path whose base diff silently failed would lint an
    empty set and report a false clean, so the caller must fall back to
    the full run instead."""
    import subprocess

    def run(cwd: Path, *args: str) -> Optional[list]:
        try:
            # quotePath=off: git's default C-quoting of non-ASCII names
            # ("na\303\257ve.py") would fail the .py suffix test and
            # silently drop the file from the changed set
            r = subprocess.run(
                ["git", "-c", "core.quotePath=off", *args], cwd=cwd,
                capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        return [ln.strip() for ln in r.stdout.splitlines() if ln.strip()]

    top = run(repo_root, "rev-parse", "--show-toplevel")
    if not top:
        return None
    # every probe runs FROM the toplevel: `ls-files` prints cwd-relative
    # paths while `diff --name-only` prints toplevel-relative ones, and
    # mixing the two anchors silently mis-resolves the changed set
    toplevel = Path(top[0])
    out: set = set()
    probes = [["diff", "--name-only", "HEAD"],
              ["ls-files", "--others", "--exclude-standard"]]
    if base:
        probes.append(["diff", "--name-only", f"{base}...HEAD"])
    for probe in probes:
        got = run(toplevel, *probe)
        if got is None:
            return None  # ANY failed probe invalidates the fast path
        out |= {(toplevel / p).resolve() for p in got if p.endswith(".py")}
    return out


def main(argv: Optional[Sequence] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id}  {rule.name}")
            print(f"       {rule.description}")
        return 0

    paths = args.paths or [_default_package_path()]
    for raw in paths:
        if not Path(raw).exists():
            print(f"error: no such path: {raw}", file=sys.stderr)
            return 2

    if args.check_imports:
        files = [p for p in paths if Path(p).is_file()]
        if files:
            # a file arg would silently widen to its parent directory and
            # fail the run on unrelated sibling modules
            print(
                f"error: --check-imports scans directories, not files: {files[0]}",
                file=sys.stderr,
            )
            return 2
        problems = check_imports(paths)
        if args.fmt == "json":
            print(json.dumps({"problems": problems}, indent=2))
        else:
            for prob in problems:
                print(prob)
            n = len(problems)
            print(f"check-imports: {n} problem{'s' if n != 1 else ''} found")
        return 1 if problems else 0

    if args.write_baseline and (args.select or args.ignore or args.changed_only):
        # a filtered run would rewrite the whole file and silently drop
        # every entry for the rules/files that didn't run
        print(
            "error: --write-baseline cannot be combined with "
            "--select/--ignore/--changed-only",
            file=sys.stderr,
        )
        return 2

    select = args.select.split(",") if args.select else None
    ignore = args.ignore.split(",") if args.ignore else None

    baseline_path = (
        Path(args.baseline) if args.baseline else baseline_mod.default_baseline_path(paths)
    )
    # With the tools/-convention baseline, anchor display paths at the repo
    # root it implies, so `lint ray_tpu/rl` or an absolute file path
    # fingerprints identically to the repo-root `lint ray_tpu/` run.
    display_root = None
    if baseline_path.is_file() and baseline_path.parent.name == "tools":
        display_root = baseline_path.resolve().parent.parent
        if any(display_path_for(Path(p), display_root) is None for p in paths):
            display_root = None  # a target outside the repo: fall back

    def scan_prefix(p: str) -> str:
        d = display_path_for(Path(p), display_root)
        if d is not None:
            return d + "/" if Path(p).is_dir() else d
        return (Path(p).resolve().name + "/") if Path(p).is_dir() else Path(p).as_posix()

    report_only: Optional[set] = None
    if args.changed_only:
        if display_root is not None:
            root = display_root
        else:
            # anchor git at the tree being linted, not the process cwd —
            # linting a checkout elsewhere must diff THAT repo
            first = Path(paths[0]).resolve()
            root = first if first.is_dir() else first.parent
        changed = _git_changed_files(root, args.changed_base)
        if changed is None:
            # no git / not a repo / unresolvable --changed-base: a fast
            # path that lints NOTHING would read as a clean bill of
            # health — fall back to the full run
            print(
                "warning: --changed-only could not query git; "
                "linting everything",
                file=sys.stderr,
            )
        else:
            # already resolved ABSOLUTE paths: display conventions vary
            # with the baseline anchoring, and a convention mismatch
            # would skip every file and report a false clean (run_paths
            # matches report_only against ctx.path, not display paths)
            report_only = changed
            if not report_only:
                if args.profile_json:
                    # the promised artifact must exist even on the quiet
                    # early exit, or a CI upload/parse step breaks
                    Path(args.profile_json).write_text(json.dumps({
                        "files": 0, "parse_s": 0.0, "index_s": 0.0,
                        "rules_s": {}, "total_s": 0.0,
                        "changed_only_empty": True,
                    }, indent=2))
                print("raylint: no changed python files")
                return 0

    prof: Optional[dict] = {} if (args.profile or args.profile_json) else None
    try:
        violations = run_paths(
            paths, select=select, ignore=ignore, display_root=display_root,
            profile=prof, report_only=report_only,
        )
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if prof is not None and args.profile:
        print(
            f"raylint profile: {prof['files']} files, "
            f"parse {prof['parse_s']}s, index {prof['index_s']}s, "
            f"total {prof['total_s']}s",
            file=sys.stderr,
        )
        for rid, secs in prof["rules_s"].items():
            print(f"  {rid}: {secs}s", file=sys.stderr)
    if prof is not None and args.profile_json:
        try:
            Path(args.profile_json).write_text(json.dumps(prof, indent=2))
        except OSError as e:
            print(f"error: cannot write {args.profile_json}: {e}", file=sys.stderr)
            return 2

    if args.write_baseline:
        if baseline_path.is_file():
            # a partial scan must not silently drop entries for files the
            # run never looked at (same hazard the --select guard covers)
            prefixes = tuple(scan_prefix(p) for p in paths)
            try:
                existing = baseline_mod.load(baseline_path)
            except (ValueError, OSError) as e:
                print(
                    f"error: unreadable baseline {baseline_path}: {e}",
                    file=sys.stderr,
                )
                return 2
            orphaned = [
                fp for fp in existing
                if not fp.split(":", 2)[1].startswith(prefixes)
            ]
            if orphaned:
                print(
                    f"error: --write-baseline would drop {len(orphaned)} "
                    "entr(y/ies) for paths outside this scan "
                    f"(e.g. {orphaned[0]}); rerun over the full tree",
                    file=sys.stderr,
                )
                return 2
        n = baseline_mod.write(baseline_path, violations)
        print(f"wrote {n} violation{'s' if n != 1 else ''} to {baseline_path}")
        return 0

    n_baselined = 0
    stale: list = []
    if not args.no_baseline and baseline_path.is_file():
        try:
            entries = baseline_mod.load(baseline_path)
        except (ValueError, OSError) as e:
            print(f"error: unreadable baseline {baseline_path}: {e}", file=sys.stderr)
            return 2
        violations, n_baselined, stale = baseline_mod.apply(violations, entries)
        # entries for files outside this scan are not stale, just unscanned
        scan_prefixes = tuple(scan_prefix(p) for p in paths)
        stale = [fp for fp in stale if fp.split(":", 2)[1].startswith(scan_prefixes)]

    if args.fmt == "json":
        print(
            json.dumps(
                {
                    "violations": [v.to_dict() for v in violations],
                    "baselined": n_baselined,
                    "stale_baseline_entries": stale,
                },
                indent=2,
            )
        )
    elif args.fmt == "github":
        # GitHub workflow commands: rendered as inline PR annotations when
        # printed from an Actions step. Newlines in the message must be
        # %0A-escaped per the workflow-command spec.
        for v in violations:
            msg = v.message.replace("%", "%25").replace("\n", "%0A")
            print(
                f"::error file={v.path},line={v.line},"
                f"col={max(v.col, 1)},title={v.rule} {_rule_name(v.rule)}::"
                f"{msg}"
            )
        print(
            f"raylint: {len(violations)} violation"
            f"{'s' if len(violations) != 1 else ''}"
        )
    else:
        for v in violations:
            print(v.render())
        summary = f"raylint: {len(violations)} violation{'s' if len(violations) != 1 else ''}"
        if n_baselined:
            summary += f" ({n_baselined} baselined)"
        print(summary)
        if stale:
            print(
                f"note: {len(stale)} stale baseline entr"
                f"{'ies' if len(stale) != 1 else 'y'} no longer match; "
                "regenerate with --write-baseline to shrink the baseline"
            )
    return 1 if violations else 0
