"""raylint: AST-based distributed-correctness linter for ray_tpu.

A Ray-class runtime fails in production through a small set of recurring
programmer errors — nested blocking ``get()`` deadlocks, unserializable
closure captures, blocking calls inside async actors — that runtime
machinery only surfaces after deployment. raylint catches them ahead of
time from the AST, with per-rule suppression comments and a baseline file
so pre-existing violations can be burned down incrementally. Beyond the
per-file rules it is a five-phase whole-program analysis: the project
index (``index.py``), per-function CFG + dataflow (``dataflow.py``), the
thread-root/shared-state model (``concurrency.py``) and the mesh/SPMD
model (``spmd.py``) feed 24 rules spanning actor hygiene, lock order,
donation/retrace dataflow, cross-thread races, wire-protocol drift and
mesh/sharding/Pallas contracts.

Run it as ``python -m ray_tpu.lint [paths]``. Library entry points:

    from ray_tpu._lint import run_paths, all_rules
    violations = run_paths(["ray_tpu"])

The package deliberately depends only on the stdlib (``ast``, ``tokenize``,
``json``) plus the AST-level serializability tables in
``ray_tpu.util.check_serialize`` (imported lazily with a fallback), so the
linter runs in any environment that can parse the source — no jax, no
cluster, no initialized runtime.
"""

from ray_tpu._lint.core import (  # noqa: F401
    FileContext,
    Rule,
    Violation,
    all_rules,
    get_rule,
    run_paths,
)
from ray_tpu._lint import rules  # noqa: F401  (imports register the rules)
