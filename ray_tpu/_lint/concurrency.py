"""raylint phase 1.9: the thread-root / shared-state model (RL017, RL018).

PR 14 made the task plane fire-and-forget and multiplied its concurrency
surface: submit outboxes flushed by a backstop thread, an off-path reply
flusher, credit/window state touched by both the ack-processing recv
thread and submitters, reconnect sweeps racing in-flight sends. Every
post-review hardening round on PRs 11-14 found exactly this bug class by
hand. This module mechanizes that review, RacerD-style:

* **Thread roots** — every spawn site the index recorded
  (``threading.Thread(target=...)`` incl. lambda bodies, executor
  ``.submit()``/``run_in_executor`` hand-offs) resolved to the function
  the new thread runs. A target that is a nested def (opaque to
  ``resolve_call``) falls back to the ENCLOSING function as the root
  body: the scanner folded the nested body's accesses into it, so they
  are attributed to the right thread (plus the spawner's own accesses —
  a documented over-approximation). One synthetic ``<caller>`` root
  stands for everything an external thread can invoke directly: the
  closure of functions with no resolvable project callers (public entry
  points), excluding pure thread bodies and ``__init__``.
* **Reachability with must-held locks** — per root, a worklist pass over
  resolvable calls computes the lock set DEFINITELY held at each
  function's entry (intersection over call paths, union with the locks
  held at each call site — ``CallSite.held_rt``, which also counts
  linear ``.acquire()``/``.release()`` bracketing). An access site's
  guard set is entry-held ∪ site-held.
* **Guarded-by inference** — for every shared-state node (a class
  attribute resolved through self/annotated-param chains, or a module
  global accessed under a ``global`` decl / without local shadowing),
  the inferred guard is the INTERSECTION of lock sets across all its
  access sites. RL017 fires when ≥2 distinct roots reach the state, at
  least one access writes, and the intersection is empty.
* **LOCKFREE declarations** — deliberate lock-free designs are declared
  in a module-level ``LOCKFREE`` tuple next to the state they cover
  (mirroring ``LOCK_ORDER``), and the declaration is VERIFIED, not
  trusted: a bare ``"Owner._attr"`` entry asserts single-writer (error
  when ≥2 roots write), ``"Owner._attr: atomic"`` asserts every write is
  one GIL-atomic operation (plain store / subscript store / one mutating
  call — a read-modify-write ``+=`` fails), and an entry matching no
  accessed state is stale (like a stale LOCK_ORDER entry).

Precision choices (documented under-approximations, like the rest of
raylint — each one keeps a benign pattern from demanding a declaration):

* ``__init__`` bodies are pre-publication and contribute no sites.
* Plain rebinds (``x.conn = fresh``, ``x.running = False``) are
  GIL-atomic reference/flag publishes: they cannot tear, so a state
  whose every write is a plain store never fires — the residual risk is
  STALENESS, which is RL018's check-then-act territory, not corruption.
* The corrupting access is a MUTATING write (``+=`` read-modify-write,
  container mutation): RL017 fires on a pair of write sites from
  different roots with disjoint lock sets where at least one is
  aug/mutate — or, when that mutating write holds NO lock at all, on a
  conflict with any other-root access (an unguarded dict/list mutation
  can corrupt a concurrent reader mid-iteration). A mutating write whose
  sites all share one lock conflicts with nothing but other writes.
* Attributes holding thread-safe stdlib primitives (Queue/SimpleQueue/
  Event/Lock/Condition/Semaphore/ThreadPoolExecutor by constructor or
  annotation evidence) are internally synchronized and exempt.
* Read-only state and state whose every access is caller-rooted (no
  spawned-thread evidence) never fires.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from ray_tpu._lint.index import FuncInfo, ProjectIndex

#: the synthetic root standing for any externally-calling thread
CALLER = "<caller>"

_WRITE_KINDS = ("store", "aug", "mutate")

#: constructors whose product is internally synchronized — an attribute
#: holding one needs no external lock for its own method calls
_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "local", "ThreadPoolExecutor",
}

_SYNC_ANN_RE = None  # built lazily (re import kept top-level-light)


def _sync_annotation(text: str) -> bool:
    import re as _re

    global _SYNC_ANN_RE
    if _SYNC_ANN_RE is None:
        _SYNC_ANN_RE = _re.compile(
            r"\b(Event|Lock|RLock|Condition|Semaphore|Barrier|"
            r"Queue|SimpleQueue|ThreadPoolExecutor)\b"
        )
    return bool(_SYNC_ANN_RE.search(text))


@dataclasses.dataclass(frozen=True)
class Access:
    """One shared-state access as seen from one thread root."""

    state: Tuple                      # see ThreadModel._attr_state/_global_state
    root: str                         # root label (CALLER or "thread:<qualname>")
    kind: str                         # read | store | aug | mutate
    locks: frozenset                  # lock keys definitely held at the site
    node: ast.AST
    func: FuncInfo
    const_store: bool = False


def state_display(state: Tuple) -> str:
    """The LOCKFREE / diagnostic spelling of a state node:
    ``Owner._attr`` for class attributes, ``<module>.<name>`` for module
    globals (same convention as lock keys)."""
    if state[0] == "attr":
        return f"{state[2]}.{state[3]}"
    return f"{state[1]}.{state[2]}"


def parse_lockfree(entry: str) -> Tuple[str, Optional[str]]:
    """``"Owner._attr: atomic"`` -> ("Owner._attr", "atomic")."""
    if ":" in entry:
        key, _, qual = entry.partition(":")
        return key.strip(), qual.strip() or None
    return entry.strip(), None


class ThreadModel:
    """Whole-program thread-root + access model, built once per lint run
    (memoized on the index via :func:`get_model`)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        #: root label -> root body FuncInfo
        self.roots: Dict[str, FuncInfo] = {}
        #: state node -> [Access, ...]
        self.accesses: Dict[Tuple, List[Access]] = {}
        #: state display key -> state node (for LOCKFREE verification)
        self.by_display: Dict[str, List[Tuple]] = {}
        self._build_roots()
        self._collect()

    # ------------------------------------------------------------- roots

    def _spawn_sites(self):
        for info in self.index.functions.values():
            for chain, _daemon in info.thread_targets:
                yield info, chain
            for chain in info.exec_submits:
                yield info, chain

    def _build_roots(self) -> None:
        index = self.index
        spawned_bodies: Dict[str, FuncInfo] = {}
        self.spawn_fallbacks: set = set()
        for info, chain in self._spawn_sites():
            callee = index.resolve_call(info, chain)
            if callee is None and len(chain) == 1:
                # nested-def target: the scanner folded its body into the
                # enclosing function — use the spawner as the root body
                callee = info
                self.spawn_fallbacks.add(info.key)
            if callee is not None:
                spawned_bodies.setdefault(callee.key, callee)
        for key, body in spawned_bodies.items():
            self.roots[f"thread:{body.qualname}"] = body
        # caller seeds: functions no project code resolvably calls, minus
        # pure thread bodies — the public surface an external thread hits
        called: set = set()
        for info in self.index.functions.values():
            for cs in info.calls:
                callee = index.resolve_call(info, cs.chain)
                if callee is not None and callee.key != info.key:
                    called.add(callee.key)
        # A method no project code resolvably calls is usually invoked
        # through an unresolvable local receiver (`node.release(res)`) —
        # its REAL lock context is its callers', which the index cannot
        # see, and claiming "no lock" there would manufacture races. Only
        # module-level functions (the public API surface) and rpc_*
        # methods (the head's dynamic getattr dispatch — genuinely hit by
        # concurrent conn threads with no locks held) count as caller
        # seeds; everything else under-approximates.
        self.caller_seeds = [
            f
            for f in index.functions.values()
            if f.key not in called
            and f.key not in spawned_bodies
            and f.name not in ("__init__", "<module>")
            and (f.cls is None or f.name.startswith("rpc_"))
        ]

    # ----------------------------------------------- reach with held locks

    def _lock_keys(self, chains, func: FuncInfo) -> frozenset:
        out = set()
        for c in chains:
            k = self.index.lock_key(c, func)
            if k is not None:
                out.add(k)
        return frozenset(out)

    def _reach_with_held(self, bodies: List[FuncInfo]) -> Dict[str, frozenset]:
        """{function key: lock set definitely held at entry} over the
        closure of resolvable calls from ``bodies`` (a must-analysis:
        intersection over call paths)."""
        index = self.index
        entry: Dict[str, frozenset] = {b.key: frozenset() for b in bodies}
        work = list(bodies)
        while work:
            f = work.pop()
            base = entry[f.key]
            for cs in f.calls:
                callee = index.resolve_call(f, cs.chain)
                if callee is None or callee.key == f.key:
                    continue
                if callee.name == "__init__":
                    continue  # construction is pre-publication
                held = base | self._lock_keys(cs.held_rt or cs.held, f)
                cur = entry.get(callee.key)
                new = held if cur is None else (cur & held)
                if new != cur:
                    entry[callee.key] = new
                    work.append(callee)
        return entry

    # ------------------------------------------------------------ accesses

    def _attr_state(self, info: FuncInfo, chain: Tuple[str, ...]) -> Optional[Tuple]:
        """Resolve an access chain to ("attr", module, Class, attr)."""
        index = self.index
        owner = None
        rest = ()
        if info.self_name is not None and chain[0] == info.self_name:
            if info.cls is None:
                return None
            owner, rest = info.cls.key, chain[1:]
        elif chain[0] in info.param_classes:
            owner, rest = info.param_classes[chain[0]], chain[1:]
        if owner is None or not rest:
            return None
        ci = index.classes.get(owner)
        if ci is None:
            return None
        if len(rest) >= 2:
            # cross-object: `self.ctx._poisoned` resolves through the
            # member's class when the index knows it; else unattributable
            ck = ci.attr_classes.get(rest[0])
            if ck is None or index.classes.get(ck) is None:
                return None
            ci = index.classes[ck]
            owner, rest = ck, rest[1:]
            if len(rest) != 1:
                return None
        attr = rest[0]
        if attr not in ci.attr_assigns:
            return None  # methods, properties, inherited/unknown names
        kinds = [k for _in_init, k, _v in ci.attr_assigns[attr]]
        if "jit_wrapper" in kinds:
            return None
        if self._is_sync_attr(ci, attr):
            return None  # internally-synchronized primitive
        return ("attr", owner[0], owner[1], attr)

    def _is_sync_attr(self, ci, attr: str) -> bool:
        cache = getattr(ci, "_sync_attr_cache", None)
        if cache is None:
            cache = ci._sync_attr_cache = {}
        got = cache.get(attr)
        if got is None:
            got = False
            for _in_init, _k, value in ci.attr_assigns.get(attr, []):
                if isinstance(value, ast.Call):
                    d = _chain(value.func)
                    if d and d[-1] in _SYNC_CTORS:
                        got = True
                        break
            if not got:
                ann = ci.attr_annotations.get(attr)
                got = bool(ann) and _sync_annotation(ann)
            cache[attr] = got
        return got

    def _global_candidates(self, info: FuncInfo) -> dict:
        """{name: is_global} for the module-global names this function can
        touch: declared ``global``, or read without any local binding."""
        mi = self.index.modules.get(info.module)
        if mi is None:
            return {}
        names = {a.name for a in info.name_accesses}
        if not names:
            return {}
        local_stores = {
            a.name for a in info.name_accesses if a.kind in ("store", "aug")
        }
        out = {}
        for name in names:
            if name not in mi.globals and name not in _module_global_names(mi):
                continue
            if mi.globals.get(name) in ("lock", "sync"):
                continue  # the synchronization object itself
            if name in info.param_names:
                continue
            if name in info.global_decls:
                out[name] = True
            elif name not in local_stores:
                out[name] = True  # pure reads of a module global
        return out

    def _collect(self) -> None:
        index = self.index
        groups: List[Tuple[str, Dict[str, frozenset]]] = []
        for label, body in self.roots.items():
            groups.append((label, self._reach_with_held([body])))
        if self.caller_seeds:
            groups.append((CALLER, self._reach_with_held(self.caller_seeds)))
        for label, entry in groups:
            for key, entry_held in entry.items():
                func = index.functions.get(key)
                if func is None or func.name == "__init__":
                    continue
                self._collect_func(func, label, entry_held)
        for state, accs in self.accesses.items():
            self.by_display.setdefault(state_display(state), []).append(state)

    def _nested_call_locks(self, func: FuncInfo) -> Dict[str, frozenset]:
        """{nested def name: locks held at EVERY local call site} — the
        scanner modeled the nested body at its def site, so a helper
        defined before a ``with cv:`` but only called inside it gets the
        cv credited back here (intersection over call sites)."""
        got = getattr(func, "_nested_call_locks", None)
        if got is not None:
            return got
        out: Dict[str, frozenset] = {}
        for cs in func.calls:
            if len(cs.chain) != 1:
                continue
            name = cs.chain[0]
            locks = self._lock_keys(cs.held_rt or cs.held, func)
            cur = out.get(name)
            out[name] = locks if cur is None else (cur & locks)
        func._nested_call_locks = out
        return out

    def _collect_func(self, func: FuncInfo, label: str, entry_held: frozenset):
        add = self._add
        nested_locks = None
        for a in func.attr_accesses:
            state = self._attr_state(func, a.chain)
            if state is None:
                continue
            locks = entry_held | self._lock_keys(a.held, func)
            if a.nested is not None:
                if nested_locks is None:
                    nested_locks = self._nested_call_locks(func)
                locks = locks | nested_locks.get(a.nested, frozenset())
            add(state, label, a.kind, locks, a.node, func, a.const_store)
        gc = self._global_candidates(func)
        if gc:
            for a in func.name_accesses:
                if a.name not in gc:
                    continue
                if a.kind in ("store", "aug") and a.name not in func.global_decls:
                    continue  # local shadow (filtered above, belt+braces)
                state = ("global", func.module, a.name)
                locks = entry_held | self._lock_keys(a.held, func)
                add(state, label, a.kind, locks, a.node, func, False)

    def _add(self, state, label, kind, locks, node, func, const_store):
        self.accesses.setdefault(state, []).append(
            Access(
                state=state, root=label, kind=kind, locks=locks,
                node=node, func=func, const_store=const_store,
            )
        )

    # ------------------------------------------------------------- queries

    def races(self):
        """Yield (state, accesses, (s1, s2), roots) for every state node
        with a concurrency conflict — RL017's firing condition,
        pre-LOCKFREE. ``s1`` is a MUTATING write (aug/mutate: the only
        access kinds that can corrupt — plain rebinds are GIL-atomic
        publishes); ``s2`` is a conflicting access from a DIFFERENT
        thread root with a disjoint lock set: another write always
        conflicts, and when ``s1`` holds no lock at all, any access does
        (an unguarded container mutation can corrupt a concurrent
        reader). Witness pairs are deterministic (sorted by site) so
        inline suppressions stay anchored."""
        for state, accs in sorted(
            self.accesses.items(), key=lambda kv: state_display(kv[0])
        ):
            roots = {a.root for a in accs}
            if len(roots) < 2 or roots == {CALLER}:
                continue
            muts = sorted(
                (a for a in accs if a.kind in ("aug", "mutate")),
                key=_site_key,
            )
            if not muts:
                continue
            pair = None
            for s1 in muts:
                others = (
                    a for a in accs
                    if a.root != s1.root
                    and not (s1.locks & a.locks)
                    and (a.kind in _WRITE_KINDS or not s1.locks)
                )
                s2 = min(others, key=_site_key, default=None)
                if s2 is not None:
                    pair = (s1, s2)
                    break
            if pair is not None:
                yield state, accs, pair, roots


def _site_key(a: Access):
    return (a.func.ctx.display_path, getattr(a.node, "lineno", 0), a.root)


def _module_global_names(mi) -> set:
    got = getattr(mi, "_global_name_cache", None)
    if got is None:
        got = set(mi.globals)
        # module-scope assignments of any kind count (mi.globals only
        # holds names with an inferred kind)
        for stmt in mi.ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        got.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                got.add(stmt.target.id)
        mi._global_name_cache = got
    return got


def get_model(index: ProjectIndex) -> ThreadModel:
    model = getattr(index, "_thread_model", None)
    if model is None:
        model = ThreadModel(index)
        index._thread_model = model
    return model


# ------------------------------------------------------------------- RL018


@dataclasses.dataclass(frozen=True)
class CheckThenAct:
    lock: str
    attr: str
    check_node: ast.AST
    act_node: ast.AST
    gate_node: ast.AST


def check_then_act(index: ProjectIndex, info: FuncInfo) -> List[CheckThenAct]:
    """The PR 14 credit-window bug shape: an attribute READ under ``with
    L`` in one block, a WRITE of the same attribute under a SEPARATE
    ``with L`` later in the same function, with the act gated by a test
    on the checked value — the lock was released between the check and
    the act, so the checked condition can be stale by the time the act
    runs. Only fires when the gate demonstrably consumes the check (the
    If/While test reads a local bound inside the check block, or the
    attribute itself)."""
    from ray_tpu._lint.dataflow import iter_expr

    self_name = info.self_name
    if self_name is None:
        return []

    blocks: List[Tuple[str, ast.With, set, set, set]] = []
    for node in ast.walk(info.node):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        keys = []
        for item in node.items:
            chain = _chain(item.context_expr)
            if chain is None:
                continue
            k = index.lock_key(chain, info)
            if k is not None:
                keys.append(k)
        if not keys:
            continue
        reads: set = set()
        writes: set = set()
        bound: set = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                chain = _chain(sub)
                if chain and len(chain) == 2 and chain[0] == self_name:
                    if isinstance(sub.ctx, ast.Load):
                        reads.add(chain[1])
                    else:
                        writes.add(chain[1])
            elif isinstance(sub, ast.AugAssign) and isinstance(sub.target, ast.Attribute):
                chain = _chain(sub.target)
                if chain and len(chain) == 2 and chain[0] == self_name:
                    writes.add(chain[1])
                    reads.add(chain[1])
            elif isinstance(sub, ast.Assign):
                value_attrs = {
                    c[1]
                    for e in iter_expr(sub.value)
                    if isinstance(e, ast.Attribute)
                    for c in [_chain(e)]
                    if c and len(c) == 2 and c[0] == self_name
                }
                if value_attrs:
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            bound.add(t.id)
        for k in keys:
            blocks.append((k, node, reads, writes, bound))

    blocks.sort(key=lambda b: b[1].lineno)
    out: List[CheckThenAct] = []
    for i, (lk1, n1, reads1, _w1, bound1) in enumerate(blocks):
        for lk2, n2, _r2, writes2, _b2 in blocks[i + 1:]:
            if lk1 != lk2 or n2 is n1 or n2.lineno <= n1.lineno:
                continue
            if _encloses(n1, n2) or _encloses(n2, n1):
                continue  # nested withs share the outer critical section
            common = reads1 & writes2
            if not common:
                continue
            gate = _gate_between(info, n2, bound1, common, self_name)
            if gate is None:
                continue
            attr = sorted(common)[0]
            out.append(
                CheckThenAct(
                    lock=lk1, attr=attr, check_node=n1, act_node=n2,
                    gate_node=gate,
                )
            )
    return out


def _chain(expr) -> Optional[Tuple[str, ...]]:
    from ray_tpu._lint.index import dotted_parts

    return dotted_parts(expr)


def _encloses(outer: ast.AST, inner: ast.AST) -> bool:
    return any(sub is inner for sub in ast.walk(outer))


def _gate_between(info, act_with, bound, attrs, self_name):
    """The If/While ancestor of ``act_with`` whose test reads a name bound
    in the check block or the checked attribute itself."""
    from ray_tpu._lint.dataflow import iter_expr

    ctx = info.ctx
    for anc in ctx.ancestors(act_with):
        if anc is info.node:
            break
        if not isinstance(anc, (ast.If, ast.While)):
            continue
        for e in iter_expr(anc.test):
            if isinstance(e, ast.Name) and e.id in bound:
                return anc
            if isinstance(e, ast.Attribute):
                c = _chain(e)
                if c and len(c) == 2 and c[0] == self_name and c[1] in attrs:
                    return anc
    return None
