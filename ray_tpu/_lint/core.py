"""raylint core: violations, the rule registry, suppression comments and the
file-walking runner.

Design notes:

- One :class:`FileContext` is built per file and shared by every rule, so
  parse / parent-map / suppression work happens once per file, not once per
  rule. Rules are pure functions of the context: ``check(ctx) -> Iterator``.
- Suppression matches pylint/ruff conventions: a trailing
  ``# raylint: disable=RL001`` silences its own line; the same comment alone
  on a line silences the next line. ``disable=all`` silences every rule.
- Baseline fingerprints are ``rule:path:symbol`` (no line numbers), so
  unrelated edits that shift lines do not invalidate the baseline; see
  ``baseline.py``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence

RULE_ID_RE = re.compile(r"^RL\d{3}$")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # posix-style display path, stable across machines
    line: int
    col: int
    message: str
    symbol: str  # enclosing qualname, "<module>" at top level
    # last line of the anchored construct's *header*: a trailing suppression
    # comment anywhere in [line, end_line] silences the violation, so
    # multiline calls can be suppressed on their closing-paren line
    end_line: int = 0

    def fingerprint(self) -> str:
        """Baseline key. Deliberately excludes line/col so edits elsewhere in
        the file don't churn the baseline."""
        return f"{self.rule}:{self.path}:{self.symbol}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.symbol}] {self.message}"


class Rule:
    """Base class; subclasses register themselves via :func:`register`."""

    id: str = "RL000"
    name: str = "abstract"
    description: str = ""

    def check(self, ctx: "FileContext") -> Iterator[Violation]:
        raise NotImplementedError


class ProjectRule(Rule):
    """A whole-program rule: phase 1 builds one :class:`ProjectIndex` over
    every scanned file, phase 2 calls :meth:`check_project` once per run.
    Violations still anchor to a (file, line) so inline suppressions and
    baseline fingerprints work exactly like per-file rules."""

    def check(self, ctx: "FileContext") -> Iterator[Violation]:
        return iter(())

    def check_project(self, index) -> Iterator[Violation]:
        raise NotImplementedError


_REGISTRY: dict[str, Rule] = {}


def register(cls: type) -> type:
    rule = cls()
    if not RULE_ID_RE.match(rule.id):
        raise ValueError(f"bad rule id {rule.id!r}")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> List[Rule]:
    return [r for _, r in sorted(_REGISTRY.items())]


def get_rule(rule_id: str) -> Optional[Rule]:
    return _REGISTRY.get(rule_id)


# --------------------------------------------------------------- suppression

_SUPPRESS_RE = re.compile(r"#\s*raylint:\s*disable=([A-Za-z0-9_,\s]+)")


def parse_suppressions(source: str) -> dict[int, set]:
    """Map line number -> set of rule ids (upper-cased; may contain "ALL")."""
    lines = source.splitlines()
    comments: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # Tolerate partially-tokenizable sources: fall back to a line scan.
        for i, ln in enumerate(lines, 1):
            if "#" in ln:
                comments.append((i, ln[ln.index("#"):]))
    out: dict[int, set] = {}
    for lineno, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = {s.strip().upper() for s in m.group(1).split(",") if s.strip()}
        line_text = lines[lineno - 1].strip() if lineno - 1 < len(lines) else ""
        # standalone comment applies to the following line, trailing to its own
        target = lineno + 1 if line_text.startswith("#") else lineno
        out.setdefault(target, set()).update(ids)
    return out


# --------------------------------------------------------------- AST helpers


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_remote_decorator(dec: ast.AST) -> bool:
    """Matches ``@remote``, ``@ray_tpu.remote``, ``@remote(...)`` and
    ``@ray_tpu.remote(num_cpus=...)``."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    d = dotted_name(target)
    return d is not None and (d == "remote" or d.endswith(".remote"))


def is_remote_def(node: ast.AST) -> bool:
    return isinstance(
        node, (ast.FunctionDef, ast.AsyncFunctionDef)
    ) and any(is_remote_decorator(d) for d in node.decorator_list)


_ACTOR_CLASS_RE = re.compile(r"Actor$|Controller$|Replica$")


def is_actor_class(node: ast.AST) -> bool:
    """Heuristic: ``@remote``-decorated classes, plus the repo's naming
    convention for classes wrapped at the call site
    (``ray_tpu.remote(num_cpus=0)(ProxyActor)``)."""
    if not isinstance(node, ast.ClassDef):
        return False
    if any(is_remote_decorator(d) for d in node.decorator_list):
        return True
    return bool(_ACTOR_CLASS_RE.search(node.name))


class FileContext:
    """Per-file shared state handed to every rule."""

    def __init__(self, path: Path, display_path: str, source: str, tree: ast.Module):
        self.path = path
        self.display_path = display_path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.suppressions = parse_suppressions(source)
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    # -- structure -------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def qualname(self, node: ast.AST) -> str:
        """Dotted chain of enclosing class/function names, including ``node``
        itself when it is a def/class. ``<module>`` at top level."""
        parts: list[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def remote_scopes(self) -> List[ast.AST]:
        """Defs whose bodies execute inside a worker: ``@remote`` functions
        plus every method of an actor-ish class. Cached."""
        cached = getattr(self, "_remote_scopes", None)
        if cached is not None:
            return cached
        scopes: list[ast.AST] = []
        for node in ast.walk(self.tree):
            if is_remote_def(node):
                scopes.append(node)
            elif is_actor_class(node):
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        if stmt not in scopes:
                            scopes.append(stmt)
        self._remote_scopes = scopes
        return scopes

    # -- emission --------------------------------------------------------

    def violation(self, rule: Rule, node: ast.AST, message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        # suppression range: the construct's header only, not its body — a
        # disable buried deep inside a with/except *block* must not count
        if isinstance(node, (ast.With, ast.AsyncWith)):
            end = max(
                (it.context_expr.end_lineno or line for it in node.items), default=line
            )
        elif isinstance(node, ast.ExceptHandler):
            end = (node.type.end_lineno or line) if node.type else line
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            end = node.body[0].lineno - 1 if node.body else line
        else:
            end = getattr(node, "end_lineno", None) or line
        return Violation(
            rule=rule.id,
            path=self.display_path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            symbol=self.qualname(node),
            end_line=max(end, line),
        )

    def is_suppressed(self, v: Violation) -> bool:
        for line in range(v.line, max(v.end_line, v.line) + 1):
            ids = self.suppressions.get(line, set())
            if ids and (v.rule.upper() in ids or "ALL" in ids):
                return True
        return False


# --------------------------------------------------------------- file runner

_SKIP_DIRS = {"__pycache__", ".git", "_dashboard_static", "node_modules"}


def display_path_for(path: Path, display_root: Optional[Path]) -> Optional[str]:
    """Repo-root-relative display for ``path`` when it lives under
    ``display_root``; None otherwise (caller falls back)."""
    if display_root is None:
        return None
    try:
        return path.resolve().relative_to(display_root).as_posix()
    except ValueError:
        return None


def iter_python_files(paths: Sequence, display_root: Optional[Path] = None) -> List[tuple]:
    """Expand files/dirs into ``(abs_path, display_path)`` pairs.

    With ``display_root`` (the repo root inferred from the baseline
    location), displays are root-relative — so scanning ``ray_tpu/rl`` or an
    absolute file path fingerprints identically to scanning ``ray_tpu/``
    from the repo root. Without it, directory inputs display as
    ``<root_basename>/<relative>`` and files as given."""
    out: list[tuple] = []
    seen: set = set()  # overlapping args (`lint ray_tpu/rl ray_tpu/`) lint once

    def add(abs_path: Path, display: str) -> None:
        if abs_path not in seen:
            seen.add(abs_path)
            out.append((abs_path, display))

    for raw in paths:
        p = Path(raw)
        if p.is_file():
            display = display_path_for(p, display_root)
            if display is None:
                display = p.as_posix()
                if display.startswith("./"):
                    display = display[2:]
            add(p.resolve(), display)
        elif p.is_dir():
            root = p.resolve()
            for f in sorted(root.rglob("*.py")):
                if any(part in _SKIP_DIRS for part in f.parts):
                    continue
                display = display_path_for(f, display_root)
                if display is None:
                    display = (Path(root.name) / f.relative_to(root)).as_posix()
                add(f, display)
        else:
            raise FileNotFoundError(f"no such file or directory: {raw}")
    return out


def _selected_rules(select: Optional[Iterable], ignore: Optional[Iterable]) -> List[Rule]:
    rules = all_rules()
    known = {r.id for r in rules}
    # a typo'd id must be an error, not a run that lints nothing and
    # reports clean
    unknown = [
        s for s in list(select or []) + list(ignore or []) if s.upper() not in known
    ]
    if unknown:
        raise ValueError(f"unknown rule id(s): {', '.join(unknown)}")
    if select:
        wanted = {s.upper() for s in select}
        rules = [r for r in rules if r.id in wanted]
    if ignore:
        dropped = {s.upper() for s in ignore}
        rules = [r for r in rules if r.id not in dropped]
    return rules


def run_paths(
    paths: Sequence,
    select: Optional[Iterable] = None,
    ignore: Optional[Iterable] = None,
    display_root: Optional[Path] = None,
    profile: Optional[dict] = None,
    report_only: Optional[set] = None,
) -> List[Violation]:
    """Lint every python file under ``paths``; returns violations that are not
    suppressed by inline comments (baseline filtering is the caller's job).

    Two phases: per-file rules run over each :class:`FileContext`; then,
    when any :class:`ProjectRule` is selected, a :class:`ProjectIndex` is
    built over ALL parsed files and the cross-module rules run against it.
    Pass a dict as ``profile`` to receive wall-time per phase and per rule
    (the CLI's ``--profile``).

    ``report_only`` (a set of RESOLVED ABSOLUTE ``Path``s) restricts which
    files may REPORT violations — the ``--changed-only`` fast path.
    Absolute paths, not display paths: display conventions vary with the
    baseline anchoring, and a convention mismatch here would silently
    report clean (the false bill of health the fast path must never
    give).  The index is still built over every scanned file (a
    whole-program analysis judged from a partial index would silently
    under-approximate), but per-file rules skip unlisted contexts and
    project-rule violations anchored outside the set are dropped."""
    import time as _time

    t_start = _time.perf_counter()
    rules = _selected_rules(select, ignore)
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    violations: list[Violation] = []
    contexts: list[FileContext] = []
    rule_times: dict[str, float] = {r.id: 0.0 for r in rules}

    t0 = _time.perf_counter()
    for abs_path, display in iter_python_files(paths, display_root=display_root):
        try:
            source = abs_path.read_text(encoding="utf-8", errors="replace")
        except OSError as e:
            violations.append(
                Violation("RL000", display, 1, 0, f"unreadable file: {e}", "<module>")
            )
            continue
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            violations.append(
                Violation(
                    "RL000", display, e.lineno or 1, e.offset or 0,
                    f"syntax error: {e.msg}", "<module>",
                )
            )
            continue
        contexts.append(FileContext(abs_path, display, source, tree))
    t_parse = _time.perf_counter() - t0

    for ctx in contexts:
        if report_only is not None and ctx.path not in report_only:
            continue
        for rule in file_rules:
            t0 = _time.perf_counter()
            for v in rule.check(ctx):
                if not ctx.is_suppressed(v):
                    violations.append(v)
            rule_times[rule.id] += _time.perf_counter() - t0

    t_index = 0.0
    if project_rules:
        from ray_tpu._lint.index import build_index

        t0 = _time.perf_counter()
        index = build_index(contexts, display_root=display_root)
        t_index = _time.perf_counter() - t0
        by_display = {ctx.display_path: ctx for ctx in contexts}
        for rule in project_rules:
            t0 = _time.perf_counter()
            for v in rule.check_project(index):
                ctx = by_display.get(v.path)
                if report_only is not None and (
                    ctx is None or ctx.path not in report_only
                ):
                    continue
                if ctx is None or not ctx.is_suppressed(v):
                    violations.append(v)
            rule_times[rule.id] += _time.perf_counter() - t0

    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    if profile is not None:
        profile.update(
            files=len(contexts),
            parse_s=round(t_parse, 4),
            index_s=round(t_index, 4),
            rules_s={k: round(v, 4) for k, v in sorted(rule_times.items())},
            total_s=round(_time.perf_counter() - t_start, 4),
        )
    return violations
